"""Operator forward vs numpy + backward vs numeric gradient
(ref: tests/python/unittest/test_operator.py — the same strategy, scaled
to the round-1 op set; grows with every op group)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd as ag
from incubator_mxnet_tpu.test_utils import (assert_almost_equal,
                                            check_numeric_gradient, same)


# ---------------------------------------------------------------------------
# unary math vs numpy reference
# ---------------------------------------------------------------------------
_UNARY_CASES = [
    ("abs", np.abs, (-2, 2)), ("square", np.square, (-2, 2)),
    ("sqrt", np.sqrt, (0.1, 4)), ("exp", np.exp, (-2, 2)),
    ("log", np.log, (0.1, 4)), ("log1p", np.log1p, (0.1, 4)),
    ("expm1", np.expm1, (-1, 1)), ("sin", np.sin, (-3, 3)),
    ("cos", np.cos, (-3, 3)), ("tanh", np.tanh, (-2, 2)),
    ("arcsin", np.arcsin, (-0.9, 0.9)), ("arctan", np.arctan, (-2, 2)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-3, 3)),
    ("relu", lambda x: np.maximum(x, 0), (-2, 2)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.5, 4)),
    ("reciprocal", lambda x: 1 / x, (0.5, 4)),
    ("cbrt", np.cbrt, (0.1, 8)),
    ("erf", None, (-2, 2)),
]


@pytest.mark.parametrize("opname,ref,rng", _UNARY_CASES,
                         ids=[c[0] for c in _UNARY_CASES])
def test_unary_forward(opname, ref, rng):
    x = np.random.uniform(rng[0], rng[1], size=(3, 4)).astype("float32")
    out = getattr(nd, opname)(nd.array(x))
    if ref is None:
        import math
        ref_vals = np.vectorize(math.erf)(x).astype("float32")
    else:
        ref_vals = ref(x)
    assert_almost_equal(out, ref_vals, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("opname", ["tanh", "sigmoid", "exp", "square",
                                    "sqrt", "log"])
def test_unary_backward_numeric(opname):
    x = np.random.uniform(0.5, 2.0, size=(3, 3)).astype("float64")
    check_numeric_gradient(lambda a: getattr(nd, opname)(a), [x])


# ---------------------------------------------------------------------------
# NN ops
# ---------------------------------------------------------------------------

def test_fully_connected():
    x = np.random.randn(4, 8).astype("float32")
    w = np.random.randn(5, 8).astype("float32")
    b = np.random.randn(5).astype("float32")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=5)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4, atol=1e-4)
    check_numeric_gradient(
        lambda a, ww, bb: nd.FullyConnected(a, ww, bb, num_hidden=5),
        [x.astype("float64"), w.astype("float64"), b.astype("float64")],
        rtol=2e-2, atol=2e-2)


def test_convolution_forward():
    # reference check against scipy-free direct computation
    x = np.random.randn(2, 3, 5, 5).astype("float32")
    w = np.random.randn(4, 3, 3, 3).astype("float32")
    b = np.zeros(4, "float32")
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4).asnumpy()
    assert out.shape == (2, 4, 3, 3)
    # manual conv at one position
    expect00 = (x[0, :, 0:3, 0:3] * w[1]).sum()
    assert abs(out[0, 1, 0, 0] - expect00) < 1e-3
    # stride + pad shape math
    out2 = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                          num_filter=4, stride=(2, 2), pad=(1, 1),
                          no_bias=True)
    assert out2.shape == (2, 4, 3, 3)


def test_convolution_backward_numeric():
    x = np.random.randn(1, 2, 4, 4).astype("float64")
    w = np.random.randn(2, 2, 3, 3).astype("float64")
    check_numeric_gradient(
        lambda a, ww: nd.Convolution(a, ww, None, kernel=(3, 3),
                                     num_filter=2, no_bias=True),
        [x, w], rtol=2e-2, atol=2e-2)


def test_pooling():
    x = np.random.randn(2, 3, 6, 6).astype("float32")
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max")
    expect = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(out, expect)
    out_avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                         pool_type="avg")
    expect_avg = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    assert_almost_equal(out_avg, expect_avg, rtol=1e-4)
    gp = nd.Pooling(nd.array(x), global_pool=True, pool_type="max")
    assert_almost_equal(gp, x.max(axis=(2, 3), keepdims=True))


def test_batchnorm_training_stats():
    x = np.random.randn(8, 4, 3, 3).astype("float32") * 3 + 1
    gamma = np.ones(4, "float32")
    beta = np.zeros(4, "float32")
    mean = np.zeros(4, "float32")
    var = np.ones(4, "float32")
    with ag.record():
        out, m, v = nd.BatchNorm(
            nd.array(x), nd.array(gamma), nd.array(beta),
            nd.array(mean), nd.array(var), fix_gamma=False)
    xm = x.mean(axis=(0, 2, 3))
    assert_almost_equal(m, xm, rtol=1e-3, atol=1e-3)
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-2
    assert abs(o.std() - 1) < 1e-2


def test_layernorm():
    x = np.random.randn(4, 6).astype("float32")
    g = np.random.rand(6).astype("float32") + 0.5
    b = np.random.randn(6).astype("float32")
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b))
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, (x - mu) / sd * g + b, rtol=1e-4, atol=1e-4)
    check_numeric_gradient(
        lambda a, gg, bb: nd.LayerNorm(a, gg, bb),
        [x.astype("float64"), g.astype("float64"), b.astype("float64")],
        rtol=2e-2, atol=2e-2)


def test_softmax_ops():
    x = np.random.randn(3, 5).astype("float32")
    out = nd.softmax(nd.array(x))
    e = np.exp(x - x.max(-1, keepdims=True))
    assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-4)
    assert_almost_equal(nd.log_softmax(nd.array(x)),
                        np.log(e / e.sum(-1, keepdims=True)),
                        rtol=1e-4, atol=1e-5)
    check_numeric_gradient(lambda a: nd.softmax(a), [x.astype("float64")])


def test_activation_types():
    x = np.random.randn(3, 4).astype("float32")
    for act, ref in [
            ("relu", np.maximum(x, 0)),
            ("sigmoid", 1 / (1 + np.exp(-x))),
            ("tanh", np.tanh(x)),
            ("softrelu", np.log1p(np.exp(x))),
            ("softsign", x / (1 + np.abs(x)))]:
        assert_almost_equal(nd.Activation(nd.array(x), act_type=act), ref,
                            rtol=1e-4, atol=1e-5)


def test_leaky_relu_family():
    x = np.random.randn(3, 4).astype("float32")
    assert_almost_equal(nd.LeakyReLU(nd.array(x), act_type="leaky",
                                     slope=0.1),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-4)
    assert_almost_equal(nd.LeakyReLU(nd.array(x), act_type="elu",
                                     slope=1.0),
                        np.where(x > 0, x, np.expm1(x)), rtol=1e-4,
                        atol=1e-5)


def test_embedding():
    w = np.random.randn(10, 4).astype("float32")
    idx = np.array([1, 5, 1, 9])
    out = nd.Embedding(nd.array(idx, dtype="int32"), nd.array(w),
                       input_dim=10, output_dim=4)
    assert_almost_equal(out, w[idx])
    # gradient accumulates duplicate rows
    wn = nd.array(w)
    wn.attach_grad()
    with ag.record():
        y = nd.Embedding(nd.array(idx, dtype="int32"), wn,
                         input_dim=10, output_dim=4).sum()
    y.backward()
    g = wn.grad.asnumpy()
    assert g[1].sum() == pytest.approx(8.0)   # row 1 used twice
    assert g[0].sum() == 0


def test_dropout_modes():
    x = nd.ones((100, 100))
    with ag.record():
        y = nd.Dropout(x, p=0.5)
    frac = float((y.asnumpy() == 0).mean())
    assert 0.4 < frac < 0.6
    y_eval = nd.Dropout(x, p=0.5)
    assert same(y_eval, np.ones((100, 100)))


def test_rnn_op_shapes():
    T, B, I, H = 4, 2, 3, 5
    from incubator_mxnet_tpu.ops.rnn import rnn_param_size
    for mode, nstate in [("lstm", 2), ("gru", 1), ("rnn_tanh", 1)]:
        psize = rnn_param_size(mode, 2, I, H, True)
        params = nd.array(np.random.randn(psize).astype("float32") * 0.1)
        state = nd.zeros((4, B, H))
        data = nd.array(np.random.randn(T, B, I).astype("float32"))
        if mode == "lstm":
            out = nd.RNN(data, params, state, nd.zeros((4, B, H)),
                         state_size=H, num_layers=2, bidirectional=True,
                         mode=mode)
            y, hT, cT = out
            assert cT.shape == (4, B, H)
        else:
            y, hT = nd.RNN(data, params, state, None, state_size=H,
                           num_layers=2, bidirectional=True, mode=mode)
        assert y.shape == (T, B, 2 * H)
        assert hT.shape == (4, B, H)


def test_lstm_cell_equivalence():
    """Fused RNN (1-layer unidirectional lstm) vs manual cell math."""
    T, B, I, H = 3, 2, 4, 5
    from incubator_mxnet_tpu.ops.rnn import rnn_param_size
    psize = rnn_param_size("lstm", 1, I, H)
    pvec = np.random.randn(psize).astype("float32") * 0.2
    data = np.random.randn(T, B, I).astype("float32")
    y, hT, cT = nd.RNN(nd.array(data), nd.array(pvec), nd.zeros((1, B, H)),
                       nd.zeros((1, B, H)), state_size=H, num_layers=1,
                       mode="lstm")
    # manual
    off = 0
    wx = pvec[off:off + 4 * H * I].reshape(4 * H, I); off += 4 * H * I
    wh = pvec[off:off + 4 * H * H].reshape(4 * H, H); off += 4 * H * H
    bx = pvec[off:off + 4 * H]; off += 4 * H
    bh = pvec[off:off + 4 * H]
    h = np.zeros((B, H), "float32")
    c = np.zeros((B, H), "float32")

    def sig(v):
        return 1 / (1 + np.exp(-v))
    for t in range(T):
        gates = data[t] @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
    assert_almost_equal(hT.asnumpy()[0], h, rtol=1e-3, atol=1e-4)
    assert_almost_equal(cT.asnumpy()[0], c, rtol=1e-3, atol=1e-4)


def test_ctc_loss_simple():
    # trivially-decodable case: loss should be low for matching logits
    T, B, A, L = 4, 1, 3, 2
    logits = np.full((T, B, A), -5.0, "float32")
    # labels 1,2 with blanks: make path blank-1-2-blank likely
    logits[0, 0, 0] = 5
    logits[1, 0, 1] = 5
    logits[2, 0, 2] = 5
    logits[3, 0, 0] = 5
    label = np.array([[1, 2]], "float32")
    loss = nd.CTCLoss(nd.array(logits), nd.array(label))
    assert loss.shape == (1,)
    assert float(loss.asscalar()) < 1.0
    # random logits → higher loss
    rnd_logits = np.random.randn(T, B, A).astype("float32")
    loss2 = nd.CTCLoss(nd.array(rnd_logits), nd.array(label))
    assert float(loss2.asscalar()) > float(loss.asscalar())


def test_control_flow_ops():
    from incubator_mxnet_tpu.ops.control_flow import (foreach, while_loop,
                                                      cond)
    import jax.numpy as jnp
    xs = jnp.arange(5.0)
    outs, final = foreach(lambda x, s: (x + s, s + 1.0), xs, jnp.zeros(()))
    assert final == 5.0
    assert np.allclose(np.asarray(outs), [0, 2, 4, 6, 8])
    _, out = while_loop(lambda v: v < 10.0,
                        lambda v: (v, v * 2), jnp.asarray(1.0))
    assert float(out) == 16.0
    res = cond(lambda v: v > 0, lambda v: v * 2, lambda v: v - 1,
               jnp.asarray(3.0))
    assert float(res) == 6.0


def test_optimizer_update_ops():
    w = np.random.randn(4).astype("float32")
    g = np.random.randn(4).astype("float32")
    out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1)
    assert_almost_equal(out, w - 0.1 * g, rtol=1e-5)
    m = np.zeros(4, "float32")
    new_w, new_m = nd.sgd_mom_update(nd.array(w), nd.array(g), nd.array(m),
                                     lr=0.1, momentum=0.9, wd=0.0,
                                     rescale_grad=1.0, clip_gradient=-1)
    assert_almost_equal(new_m, -0.1 * g, rtol=1e-5)
    assert_almost_equal(new_w, w - 0.1 * g, rtol=1e-5)
    mean = np.zeros(4, "float32")
    var = np.zeros(4, "float32")
    new_w, new_mean, new_var = nd.adam_update(
        nd.array(w), nd.array(g), nd.array(mean), nd.array(var),
        lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
        rescale_grad=1.0, clip_gradient=-1)
    assert_almost_equal(new_mean, 0.1 * g, rtol=1e-4)


def test_norm_ops():
    x = np.random.randn(3, 4).astype("float32")
    assert_almost_equal(nd.L2Normalization(nd.array(x)),
                        x / np.sqrt((x ** 2).sum(axis=1,
                                    keepdims=True) + 1e-10),
                        rtol=1e-4)
    assert_almost_equal(nd.norm(nd.array(x), axis=1),
                        np.linalg.norm(x, axis=1), rtol=1e-4)


def test_smooth_l1():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], "float32")
    out = nd.smooth_l1(nd.array(x), scalar=1.0)
    expect = np.where(np.abs(x) < 1, 0.5 * x ** 2, np.abs(x) - 0.5)
    assert_almost_equal(out, expect)


def test_grouped_deconvolution():
    """Grouped transposed conv == concat of per-group transposed convs,
    and matches the gradient-of-conv identity per group."""
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(2, 4, 5, 5).astype(np.float32))
    w = nd.array(rs.randn(4, 3, 3, 3).astype(np.float32))  # g=2: 2->3 each
    out = nd.invoke("Deconvolution", x, w, None, kernel=(3, 3),
                    stride=(2, 2), pad=(1, 1), num_filter=6, num_group=2,
                    no_bias=True)
    assert out.shape == (2, 6, 9, 9)
    # reference: run each group separately with num_group=1
    parts = []
    for g in range(2):
        xg = nd.array(x.asnumpy()[:, g * 2:(g + 1) * 2])
        wg = nd.array(w.asnumpy()[g * 2:(g + 1) * 2])
        parts.append(nd.invoke("Deconvolution", xg, wg, None,
                               kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                               num_filter=3, num_group=1,
                               no_bias=True).asnumpy())
    want = np.concatenate(parts, axis=1)
    assert np.allclose(out.asnumpy(), want, atol=1e-5)


def test_grid_generator_warp():
    """warp grid: zero flow == identity sampling grid in [-1, 1]."""
    flow = nd.array(np.zeros((1, 2, 3, 5), np.float32))
    grid = nd.invoke("GridGenerator", flow, transform_type="warp",
                     target_shape=(3, 5)).asnumpy()
    assert grid.shape == (1, 2, 3, 5)
    assert np.allclose(grid[0, 0, 0], np.linspace(-1, 1, 5), atol=1e-6)
    assert np.allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 3), atol=1e-6)
    # +1-pixel x flow shifts the normalized grid by 2/(W-1)
    flow2 = nd.array(np.stack([np.ones((1, 3, 5), np.float32),
                               np.zeros((1, 3, 5), np.float32)], axis=1))
    g2 = nd.invoke("GridGenerator", flow2, transform_type="warp",
                   target_shape=(3, 5)).asnumpy()
    assert np.allclose(g2[0, 0] - grid[0, 0], 2.0 / 4.0, atol=1e-6)


def test_fused_softmax_ce_matches_decomposed():
    """_fused_softmax_ce (memory-exact vjp: logits+lse residuals only)
    vs log_softmax+pick — forward and input gradients."""
    rs = np.random.RandomState(21)
    pred_np = (rs.randn(5, 13) * 2).astype(np.float32)
    lab = nd.array(rs.randint(0, 13, 5).astype(np.float32))

    p1 = nd.array(pred_np)
    p1.attach_grad()
    with ag.record():
        l1 = nd.invoke("_fused_softmax_ce", p1, lab, axis=-1)
        (l1 * nd.array(np.arange(1.0, 6.0, dtype=np.float32))) \
            .sum().backward()

    p2 = nd.array(pred_np)
    p2.attach_grad()
    with ag.record():
        ls = nd.log_softmax(p2, axis=-1)
        l2 = -nd.pick(ls, lab, axis=-1)
        (l2 * nd.array(np.arange(1.0, 6.0, dtype=np.float32))) \
            .sum().backward()

    np.testing.assert_allclose(l1.asnumpy(), l2.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(p1.grad.asnumpy(), p2.grad.asnumpy(),
                               rtol=1e-4, atol=1e-5)
