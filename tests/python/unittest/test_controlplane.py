"""Fleet control-plane tests (ISSUE 16 tentpole): FleetSupervisor
autoscaling hysteresis (square-wave bounded, cooldown-armed denial),
canary deploy/ramp/promote/rollback through the registry's versioned
entries, bounded-build RegistrationTimeout, version-labeled telemetry
flow, and the exactly-once HBM-ledger release invariant on every exit
path.  CPU-only, fast (the check_controlplane chaos gate is
slow-marked)."""
import os
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon, fault
from incubator_mxnet_tpu import config as cfg
from incubator_mxnet_tpu.monitor import events
from incubator_mxnet_tpu.serving import (FleetSupervisor,
                                         ModelRegistry,
                                         AdmissionDenied,
                                         RegistrationTimeout,
                                         project_footprint)
from incubator_mxnet_tpu.telemetry import flightrec as _bb
from incubator_mxnet_tpu.telemetry import slo as _slo

pytestmark = pytest.mark.controlplane


@pytest.fixture(autouse=True)
def _clean_slo_rules():
    """No SLO rule (supervisor watchdogs, canary rules, fakes) may
    leak across tests."""
    yield
    _slo.clear_rules()


def _dense_net(units=4, in_units=8, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dense(units))
    net.initialize(ctx=mx.cpu())
    net(nd.array(onp.zeros((2, in_units), onp.float32)))
    return net


def _data(n=8, in_units=8, seed=3):
    return onp.random.RandomState(seed).rand(n, in_units).astype(
        onp.float32)


def _registry(n=4, **kw):
    return ModelRegistry(devices=[mx.cpu(i) for i in range(n)], **kw)


def _committed(reg):
    return sum(r["committed"] for r in reg.stats()["ledger"])


class _FakeRule(_slo.Rule):
    """Hand-toggled rule: drives the supervisor deterministically."""

    def __init__(self, name, labels=None):
        super().__init__(name, description="test fake")
        self.firing = False
        self._labels = labels

    def check(self, now):
        info = {"burn": 9.9}
        if self._labels:
            info["labels"] = dict(self._labels)
        return bool(self.firing), info


def _sup(reg, model="m", **kw):
    kw.setdefault("install_rules", False)
    kw.setdefault("cooldown_s", 0.0)
    return FleetSupervisor(reg, model, **kw)


# -- satellite 2: bounded registration builds --------------------------
def test_registration_timeout_releases_ledger_exactly_once():
    reg = _registry(2)
    fault.install("serve.build", seconds=1.5)
    r0 = events.get("serve.registration_timeout")
    c0 = _committed(reg)
    with pytest.raises(RegistrationTimeout):
        reg.register("rt", _dense_net(seed=1), example_shape=(8,),
                     max_batch=2, build_timeout=0.05)
    # the hold rolled back: ledger where it started, name free, the
    # timeout typed + counted + on the flight recorder
    assert _committed(reg) == c0
    assert "rt" not in reg.stats()["models"]
    assert events.get("serve.registration_timeout") == r0 + 1
    ring = [e for e in _bb.ring_snapshot()
            if e.get("kind") == "serve"
            and e.get("name") == "registration_timeout"]
    assert ring and ring[-1]["model"] == "rt"
    # the same name registers cleanly once the stall is gone (the
    # abandoned builder may still be sleeping — ownership, not time,
    # is what the handshake settles)
    fault.clear("serve.build")
    reg.register("rt", _dense_net(seed=1), example_shape=(8,),
                 max_batch=2, build_timeout=30.0)
    out = reg.submit("rt", _data(1)[0]).result(timeout=30)
    assert out is not None
    reg.close()


def test_build_timeout_zero_disables_bound():
    reg = _registry(1)
    fault.install("serve.build", seconds=0.2)
    reg.register("bt", _dense_net(seed=2), example_shape=(8,),
                 max_batch=2, build_timeout=0)
    assert "bt" in reg.stats()["models"]
    reg.close()


# -- satellite 3: version-labeled serve telemetry ----------------------
def test_version_labels_flow_to_counters_and_rings():
    reg = _registry(1)
    reg.register("vl", _dense_net(seed=3), example_shape=(8,),
                 max_batch=4, version="v1")
    reg.warmup("vl")
    for x in _data(6):
        reg.submit("vl", x).result(timeout=30)
    reqs = {tuple(sorted(r["labels"].items())): r["value"]
            for r in events.labeled_snapshot().get(
                "serve.requests", [])}
    assert reqs.get((("version", "v1"),), 0) >= 6
    lat = [r for r in events.labeled_percentiles("serve.e2e_us")
           if r["labels"] == {"version": "v1"}]
    assert lat and lat[0]["n"] >= 6
    # the shed split carries the version too (expired deadline)
    s0 = sum(r["value"] for r in events.labeled_snapshot().get(
        "serve.shed", []) if r["labels"] == {"version": "v1"})
    fault.install("serve.slow", at_calls=[1], times=8, seconds=0.3)
    sheds = [reg.submit("vl", _data(1)[0])]     # occupies the
    time.sleep(0.05)                            # dispatcher in the
    for x in _data(3):                          # stall; the rest
        sheds.append(reg.submit("vl", x,        # expire in-queue
                                deadline=0.01))
    shed_n = 0
    for f in sheds:
        try:
            f.result(timeout=30)
        except Exception:           # noqa: BLE001 — typed shed family
            shed_n += 1
    assert shed_n >= 1
    s1 = sum(r["value"] for r in events.labeled_snapshot().get(
        "serve.shed", []) if r["labels"] == {"version": "v1"})
    assert s1 >= s0 + 1
    reg.close()


def test_canary_mirror_fraction_is_deterministic():
    reg = _registry(2)
    reg.register("cf", _dense_net(seed=4), example_shape=(8,),
                 max_batch=4, version="v1")
    reg.warmup("cf")
    reg.register_version("cf", _dense_net(seed=5), "v2", fraction=0.5)
    base = {r["labels"]["version"]: r["value"]
            for r in events.labeled_snapshot().get(
                "serve.requests", [])
            if "version" in r["labels"]}
    for x in _data(8):
        reg.submit("cf", x).result(timeout=30)
    now = {r["labels"]["version"]: r["value"]
           for r in events.labeled_snapshot().get(
               "serve.requests", [])
           if "version" in r["labels"]}
    # fraction 0.5 through the accumulator: EXACTLY every 2nd request
    assert now.get("v2", 0) - base.get("v2", 0) == 4
    assert now.get("v1", 0) - base.get("v1", 0) == 4
    reg.rollback_version("cf")
    reg.close()


# -- satellite 4: supervisor edge cases --------------------------------
def test_square_wave_hysteresis_bounds_transitions():
    reg = _registry(3)
    reg.register("sq", _dense_net(seed=6), max_batch=1, replicas=1)
    rule = _slo.register_rule(_FakeRule("sq-hot"))
    sup = _sup(reg, "sq", watch_rules=("sq-hot",), max_replicas=2,
               up_rounds=2, down_rounds=3, cooldown_s=8.0)
    t = [1000.0]

    def window(firing, ticks):
        rule.firing = firing
        u0 = events.get("controlplane.scale_ups")
        d0 = events.get("controlplane.scale_downs")
        for _ in range(ticks):
            sup.tick(now=t[0])
            t[0] += 1.0
        return (events.get("controlplane.scale_ups") - u0,
                events.get("controlplane.scale_downs") - d0)

    ups = downs = 0
    for _ in range(2):
        u, d = window(True, 6)
        assert u <= 1 and d == 0, "hot window: at most ONE scale-up"
        ups += u
        d, u2 = window(False, 6)[::-1]
        assert d <= 1 and u2 == 0, \
            "quiet window: at most ONE scale-down"
        downs += d
    assert ups >= 1 and downs >= 1   # the wave did move the fleet
    n = reg.stats()["models"]["sq"]["replicas"]
    assert 1 <= n <= 2
    sup.close()
    reg.close()


def test_rollback_during_ramp_is_exactly_once():
    reg = _registry(2)
    reg.register("rb", _dense_net(seed=7), example_shape=(8,),
                 max_batch=2, version="v1")
    reg.warmup("rb")
    base_committed = _committed(reg)
    bad = _slo.register_rule(_FakeRule("rb-bad",
                                       labels={"version": "v2"}))
    sup = _sup(reg, "rb", max_replicas=1, observe_rounds=1,
               canary_fraction=0.2, canary_step=0.2, canary_max=0.9)
    sup.deploy(_dense_net(seed=8), "v2")
    assert _committed(reg) > base_committed     # canary holds HBM
    sup.tick(now=2000.0)                        # quiet -> ramp
    assert reg.canary("rb")["fraction"] == pytest.approx(0.4)
    assert events.get("controlplane.ramps") >= 1
    bad.firing = True                           # breach mid-ramp
    r0 = events.get("controlplane.rollbacks")
    sup.tick(now=2001.0)
    assert events.get("controlplane.rollbacks") == r0 + 1
    assert sup.last_rollback["rule"] == "rb-bad"
    assert sup.last_rollback["version"] == "v2"
    assert sup.status()["canary"] is None
    assert reg.canary("rb") is None
    assert "rb@v2" not in reg.stats()["models"]
    # ledger hold released EXACTLY once: back to the primary's
    # footprint, and neither a second breach tick nor a manual
    # rollback releases anything again
    assert _committed(reg) == base_committed
    sup.tick(now=2002.0)
    assert sup.rollback() is None
    assert reg.rollback_version("rb") is None
    assert _committed(reg) == base_committed
    assert events.get("controlplane.rollbacks") == r0 + 1
    # the proactive dump names the incident
    assert sup.last_rollback["blackbox"]
    assert os.path.exists(sup.last_rollback["blackbox"])
    sup.close()
    reg.close()


def test_promote_waits_for_full_quiet_window():
    reg = _registry(2)
    reg.register("pm", _dense_net(seed=9), example_shape=(8,),
                 max_batch=2, version="v1")
    reg.warmup("pm")
    base_committed = _committed(reg)
    noise = _slo.register_rule(_FakeRule("pm-noise"))
    # max_replicas=1: the noise rule is scale evidence too, and this
    # test must observe the RAMP gate, not a resize
    sup = _sup(reg, "pm", watch_rules=("pm-noise",), max_replicas=1,
               observe_rounds=3, canary_fraction=0.5, canary_max=0.5)
    sup.deploy(_dense_net(seed=10), "v2")
    sup.tick(now=3000.0)
    sup.tick(now=3001.0)            # 2 quiet ticks: window not full
    assert reg.canary("pm") is not None
    noise.firing = True             # alert mid-window -> window resets
    sup.tick(now=3002.0)
    noise.firing = False
    sup.tick(now=3003.0)
    sup.tick(now=3004.0)            # only 2 quiet since the alert
    assert reg.canary("pm") is not None
    assert reg.stats()["models"]["pm"]["version"] == "v1"
    sup.tick(now=3005.0)            # 3rd quiet tick: full window ->
    assert reg.canary("pm") is None         # promote (at the ceiling)
    assert reg.stats()["models"]["pm"]["version"] == "v2"
    assert events.get("controlplane.promotes") >= 1
    # promote retired the canary entry: its hold released exactly once
    assert _committed(reg) == base_committed
    with pytest.raises(ValueError):
        sup.promote()
    assert _committed(reg) == base_committed
    # promoted weights actually serve (the swap, not the label): the
    # primary's outputs now match the promoted block's params
    out = reg.submit("pm", _data(1)[0]).result(timeout=30)
    assert out is not None
    sup.close()
    reg.close()


def test_all_replicas_unhealthy_forces_rebuild():
    reg = _registry(2)
    reg.register("hm", _dense_net(seed=11), example_shape=(8,),
                 max_batch=2, replicas=2, version="v1")
    reg.warmup("hm")
    c0 = _committed(reg)
    old = reg.engine("hm")
    old._unhealthy_until = [time.time() + 60.0] * 2
    assert all(h == "unhealthy"
               for h in old.stats()["replica_health"])
    sup = _sup(reg, "hm", max_replicas=2, cooldown_s=30.0)
    u0 = events.get("controlplane.unhealthy_fleet")
    sup.tick(now=4000.0)
    assert events.get("controlplane.unhealthy_fleet") == u0 + 1
    fresh = reg.engine("hm")
    assert fresh is not old         # emergency rebuild swapped engines
    assert all(h == "healthy" for h in fresh.stats()["replica_health"])
    assert _committed(reg) == c0    # same replica count, same ledger
    # idempotent under cooldown: the next tick must NOT rebuild again
    sup.tick(now=4001.0)
    assert reg.engine("hm") is fresh
    assert (_bb.last_dump_path() or "").find("unhealthy-hm") >= 0
    sup.close()
    reg.close()


def test_scale_denied_arms_cooldown_and_releases_nothing():
    net = _dense_net(seed=12)
    fp, _ = project_footprint(net, (1, 2), (8,), "float32")
    cfg.set("MXNET_SERVE_HBM_BUDGET", int(fp * 1.5))
    try:
        reg = _registry(1)
        reg.register("sd", net, example_shape=(8,), max_batch=2)
        c0 = _committed(reg)
        rule = _slo.register_rule(_FakeRule("sd-hot"))
        rule.firing = True
        sup = _sup(reg, "sd", watch_rules=("sd-hot",), max_replicas=2,
                   up_rounds=1, cooldown_s=10.0)
        d0 = events.get("controlplane.scale_denied")
        sup.tick(now=5000.0)
        assert events.get("controlplane.scale_denied") == d0 + 1
        assert _committed(reg) == c0    # denial left no partial hold
        assert reg.stats()["models"]["sd"]["replicas"] == 1
        # the denial armed the cooldown: no retry-flap on the next
        # ticks even though the rule still fires
        sup.tick(now=5001.0)
        sup.tick(now=5002.0)
        assert events.get("controlplane.scale_denied") == d0 + 1
        sup.close()
        reg.close()
    finally:
        cfg.unset("MXNET_SERVE_HBM_BUDGET")


def test_register_version_admission_denied_releases_hold():
    net = _dense_net(seed=13)
    fp, _ = project_footprint(net, (1, 2), (8,), "float32")
    cfg.set("MXNET_SERVE_HBM_BUDGET", int(fp * 1.5))
    try:
        reg = _registry(1)
        reg.register("ad", net, example_shape=(8,), max_batch=2,
                     version="v1")
        c0 = _committed(reg)
        with pytest.raises(AdmissionDenied):
            reg.register_version("ad", _dense_net(seed=14), "v2")
        assert _committed(reg) == c0
        assert reg.canary("ad") is None
        assert "ad@v2" not in reg.stats()["models"]
        reg.close()
    finally:
        cfg.unset("MXNET_SERVE_HBM_BUDGET")


def test_supervisor_watchdog_rules_install_and_uninstall():
    reg = _registry(1)
    reg.register("wd", _dense_net(seed=15), max_batch=1)
    sup = FleetSupervisor(reg, "wd", install_rules=True)
    names = set(_slo.rules())
    assert {"ctl-rollback-storm", "ctl-scale-oscillation"} <= names
    sup.close()
    assert not ({"ctl-rollback-storm", "ctl-scale-oscillation"}
                & set(_slo.rules()))
    reg.close()


# -- satellite 5: the chaos gate, wired for CI -------------------------
@pytest.mark.slow
def test_check_controlplane_gate():
    import subprocess
    import sys
    root = os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", ".."))
    res = subprocess.run(
        [sys.executable,
         os.path.join(root, "tools", "check_controlplane.py"),
         "--trials", "2"],
        capture_output=True, text=True, timeout=420, cwd=root)
    assert res.returncode == 0, \
        "check_controlplane failed:\n%s\n%s" % (res.stdout, res.stderr)
    assert ("OK" in res.stdout) or ("SKIP" in res.stdout)
