"""NDArray basics (ref: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import assert_almost_equal, same


def test_array_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert a.context == mx.cpu()
    b = nd.array(np.arange(6).reshape(2, 3).astype("int32"))
    assert b.dtype == np.int32
    assert same(b, np.arange(6).reshape(2, 3))


def test_zeros_ones_full():
    assert same(nd.zeros((2, 3)), np.zeros((2, 3)))
    assert same(nd.ones((2, 3)), np.ones((2, 3)))
    assert same(nd.full((2,), 7), np.full((2,), 7.0))
    assert same(nd.eye(3), np.eye(3))
    assert same(nd.arange(0, 10, 2), np.arange(0, 10, 2))


def test_elementwise_arith():
    a_np = np.random.randn(3, 4).astype("float32")
    b_np = np.random.randn(3, 4).astype("float32")
    a, b = nd.array(a_np), nd.array(b_np)
    assert_almost_equal(a + b, a_np + b_np)
    assert_almost_equal(a - b, a_np - b_np)
    assert_almost_equal(a * b, a_np * b_np)
    assert_almost_equal(a / b, a_np / b_np)
    assert_almost_equal(a + 2, a_np + 2)
    assert_almost_equal(2 - a, 2 - a_np)
    assert_almost_equal(a * 0.5, a_np * 0.5)
    assert_almost_equal(1.0 / (a + 10), 1.0 / (a_np + 10))
    assert_almost_equal(-a, -a_np)
    assert_almost_equal(abs(a), np.abs(a_np))
    assert_almost_equal((a + 10) ** 2, (a_np + 10) ** 2)


def test_inplace_ops():
    a_np = np.random.randn(3, 4).astype("float32")
    a = nd.array(a_np)
    a += 1
    assert_almost_equal(a, a_np + 1)
    a *= 2
    assert_almost_equal(a, (a_np + 1) * 2)


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert same(a == b, [0, 1, 0])
    assert same(a != b, [1, 0, 1])
    assert same(a > b, [0, 0, 1])
    assert same(a >= b, [0, 1, 1])
    assert same(a < b, [1, 0, 0])
    assert same(a <= b, [1, 1, 0])


def test_reshape_transpose():
    a_np = np.arange(24).astype("float32").reshape(2, 3, 4)
    a = nd.array(a_np)
    assert same(a.reshape(6, 4), a_np.reshape(6, 4))
    assert same(a.reshape((-1, 4)), a_np.reshape(-1, 4))
    assert same(a.reshape((0, -1)), a_np.reshape(2, 12))    # magic 0
    assert same(a.T, a_np.T)
    assert same(a.transpose((2, 0, 1)), a_np.transpose(2, 0, 1))
    assert same(a.swapaxes(0, 1), a_np.swapaxes(0, 1))
    assert same(a.flatten(), a_np.reshape(2, -1))
    assert same(a.expand_dims(1), a_np[:, None])
    assert same(nd.squeeze(a.expand_dims(0), axis=0), a_np)


def test_reshape_magic():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)
    assert a.reshape((0, 0, -1)).shape == (2, 3, 4)
    assert a.reshape((-2,)).shape == (2, 3, 4)


def test_indexing():
    a_np = np.arange(24).astype("float32").reshape(4, 6)
    a = nd.array(a_np)
    assert same(a[1], a_np[1])
    assert same(a[1:3], a_np[1:3])
    assert same(a[:, 2:4], a_np[:, 2:4])
    assert float(a[2, 3].asscalar()) == a_np[2, 3]
    idx = nd.array(np.array([0, 2]), dtype="int32")
    assert same(a[idx], a_np[[0, 2]])


def test_setitem():
    a = nd.zeros((3, 4))
    a[1] = 5.0
    expected = np.zeros((3, 4), "float32")
    expected[1] = 5
    assert same(a, expected)
    a[0, 2] = 3.0
    expected[0, 2] = 3
    assert same(a, expected)
    a[2] = nd.ones((4,))
    expected[2] = 1
    assert same(a, expected)


def test_reduce():
    a_np = np.random.rand(3, 4, 5).astype("float32")
    a = nd.array(a_np)
    assert_almost_equal(a.sum(), a_np.sum())
    assert_almost_equal(a.sum(axis=1), a_np.sum(1))
    assert_almost_equal(a.mean(axis=(0, 2)), a_np.mean((0, 2)))
    assert_almost_equal(nd.sum(a, axis=1, exclude=True),
                        a_np.sum((0, 2)))
    assert_almost_equal(a.max(axis=0), a_np.max(0))
    assert_almost_equal(a.min(axis=-1, keepdims=True),
                        a_np.min(-1, keepdims=True))
    assert_almost_equal(a.norm(), np.sqrt((a_np ** 2).sum()), rtol=1e-4)
    assert same(a.argmax(axis=2), a_np.argmax(2))
    assert same(a.argmin(axis=0), a_np.argmin(0))


def test_dot():
    a_np = np.random.randn(4, 5).astype("float32")
    b_np = np.random.randn(5, 6).astype("float32")
    assert_almost_equal(nd.dot(nd.array(a_np), nd.array(b_np)),
                        a_np @ b_np, rtol=1e-4, atol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a_np), nd.array(b_np.T), transpose_b=True),
        a_np @ b_np, rtol=1e-4, atol=1e-4)
    x = np.random.randn(3, 4, 5).astype("float32")
    y = np.random.randn(3, 5, 2).astype("float32")
    assert_almost_equal(nd.batch_dot(nd.array(x), nd.array(y)),
                        np.matmul(x, y), rtol=1e-4, atol=1e-4)


def test_concat_stack_split():
    a_np = np.random.randn(2, 3).astype("float32")
    b_np = np.random.randn(2, 3).astype("float32")
    a, b = nd.array(a_np), nd.array(b_np)
    assert same(nd.concat(a, b, dim=0), np.concatenate([a_np, b_np], 0))
    assert same(nd.concat(a, b, dim=1), np.concatenate([a_np, b_np], 1))
    assert same(nd.stack(a, b, axis=0), np.stack([a_np, b_np]))
    parts = nd.split(nd.array(np.arange(12).reshape(2, 6)), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_take_pick_gather():
    a_np = np.random.randn(5, 4).astype("float32")
    a = nd.array(a_np)
    idx = nd.array([0, 2], dtype="int32")
    assert same(nd.take(a, idx), a_np[[0, 2]])
    pick_idx = nd.array([0, 1, 2, 3, 0], dtype="int32")
    assert same(nd.pick(a, pick_idx, axis=1),
                a_np[np.arange(5), [0, 1, 2, 3, 0]])
    indices = nd.array(np.array([[1, 3], [0, 2]]), dtype="int32")
    assert same(nd.gather_nd(a, indices), a_np[[1, 3], [0, 2]])


def test_where_clip_onehot():
    a_np = np.random.randn(3, 4).astype("float32")
    a = nd.array(a_np)
    assert_almost_equal(a.clip(-0.5, 0.5), np.clip(a_np, -0.5, 0.5))
    cond = nd.array((a_np > 0).astype("float32"))
    assert same(nd.where(cond, a, -a),
                np.where(a_np > 0, a_np, -a_np))
    oh = nd.one_hot(nd.array([0, 2, 1], dtype="int32"), 3)
    assert same(oh, np.eye(3)[[0, 2, 1]])


def test_ordering():
    a_np = np.random.randn(4, 8).astype("float32")
    a = nd.array(a_np)
    assert same(nd.sort(a, axis=1), np.sort(a_np, 1))
    assert same(nd.argsort(a, axis=1), np.argsort(a_np, 1, kind="stable"))
    vals = nd.topk(a, k=3, axis=1, ret_typ="value")
    expect = -np.sort(-a_np, axis=1)[:, :3]
    assert_almost_equal(vals, expect)


def test_copy_context():
    a = nd.array([1.0, 2.0])
    b = a.copy()
    b += 1
    assert same(a, [1, 2])
    c = a.as_in_context(mx.cpu())
    assert c.context == mx.cpu()
    out = nd.zeros((2,))
    a.copyto(out)
    assert same(out, [1, 2])


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    d = {"w": nd.array(np.random.randn(3, 4).astype("float32")),
         "b": nd.array(np.random.randn(4).astype("float32"))}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert same(loaded["w"], d["w"].asnumpy())
    lst = [nd.array([1.0]), nd.array([2.0, 3.0])]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert same(loaded[0], [1]) and same(loaded[1], [2, 3])


def test_dtype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype("bfloat16")
    assert c.dtype.name == "bfloat16"
    assert_almost_equal(c.astype("float32"), [1.5, 2.5])


def test_broadcast_ops():
    a_np = np.random.randn(3, 1).astype("float32")
    b_np = np.random.randn(1, 4).astype("float32")
    a, b = nd.array(a_np), nd.array(b_np)
    assert_almost_equal(nd.broadcast_add(a, b), a_np + b_np)
    assert_almost_equal(nd.broadcast_mul(a, b), a_np * b_np)
    assert same(nd.broadcast_to(nd.array([[1.0], [2.0]]), (2, 3)),
                np.broadcast_to([[1.], [2.]], (2, 3)))
    assert_almost_equal(nd.broadcast_maximum(a, b), np.maximum(a_np, b_np))


def test_wait_and_scalar():
    a = nd.ones((2, 2))
    a.wait_to_read()
    nd.waitall()
    s = nd.array([3.5])
    assert float(s) == 3.5
    assert s.asscalar() == 3.5
    with pytest.raises(ValueError):
        nd.ones((2, 2)).asscalar()


def test_sequence_ops():
    data = nd.array(np.arange(24).reshape(4, 3, 2))  # (T,B,D)
    length = nd.array([2, 3, 1], dtype="int32")
    masked = nd.SequenceMask(data, length, use_sequence_length=True,
                             value=-1.0)
    out = masked.asnumpy()
    assert out[2, 0, 0] == -1 and out[1, 1, 0] != -1
    last = nd.SequenceLast(data, length, use_sequence_length=True)
    assert last.shape == (3, 2)
    assert last.asnumpy()[0, 0] == data.asnumpy()[1, 0, 0]
