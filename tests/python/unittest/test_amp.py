"""AMP (ref: tests/python/unittest/test_amp.py + contrib amp tests)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon, autograd as ag
from incubator_mxnet_tpu.contrib import amp


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.turn_off()


def test_amp_init_casts_target_ops():
    """After init(), FullyConnected computes in bfloat16 even on f32
    inputs; softmax stays f32."""
    amp.init("bfloat16")
    x = nd.array(np.random.rand(4, 8).astype(np.float32))
    w = nd.array(np.random.rand(16, 8).astype(np.float32))
    out = nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
    assert out.dtype == np.dtype("bfloat16") or str(out.dtype) == "bfloat16"
    s = nd.softmax(out)
    assert str(s.dtype) == "float32"   # FP32 op casts back up
    amp.turn_off()
    out2 = nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
    assert str(out2.dtype) == "float32"


def test_amp_training_bf16_converges():
    """End-to-end: init() + convert_hybrid_block + scale_loss (no-op
    scale for bf16) trains a small net."""
    amp.init("bfloat16")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    amp.convert_hybrid_block(net)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    amp.init_trainer(trainer)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(16, 8).astype(np.float32))
    y = nd.array(rs.randint(0, 4, (16,)).astype(np.float32))
    first = last = None
    for _ in range(25):
        with ag.record():
            out = net(x)
            l = loss_fn(out, y)
            with amp.scale_loss(l, trainer) as scaled:
                scaled.backward()
        trainer.step(16)
        last = float(l.asnumpy().mean())
        if first is None:
            first = last
    assert last < first * 0.7, (first, last)
    # weights really are bf16
    w = net[0].weight.data()
    assert str(w.dtype) == "bfloat16"


def test_amp_dynamic_loss_scaler_backoff():
    """fp16-style dynamic scaling: overflowed grads are zeroed and the
    scale halves; clean steps grow it after the window."""
    sc = amp.LossScaler(init_scale=1024.0, scale_factor=2.0,
                        scale_window=2)
    sc.update(overflow=True)
    assert sc.loss_scale == 512.0
    sc.update(False)
    sc.update(False)
    assert sc.loss_scale == 1024.0


def test_amp_scale_loss_overflow_zeroes_grads():
    net = gluon.nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.0})
    amp.init_trainer(trainer, amp.LossScaler(init_scale=4.0))
    x = nd.array(np.full((2, 3), 1e38, np.float32))   # overflows when scaled
    y = nd.array(np.ones((2,), np.float32))
    loss_fn = gluon.loss.L2Loss()
    with ag.record():
        l = loss_fn(net(x), y)
        with amp.scale_loss(l, trainer) as scaled:
            scaled.backward()
    g = net.weight.grad().asnumpy()
    assert np.all(g == 0.0), g
    assert trainer._amp_loss_scaler.loss_scale == 2.0   # backed off


def test_amp_convert_model_keeps_norm_stats_f32():
    sym = None
    args = {"fc_weight": nd.ones((4, 4)),
            "bn_gamma": nd.ones((4,))}
    aux = {"bn_moving_mean": nd.zeros((4,))}
    _, new_args, new_aux = amp.convert_model(sym, args, aux,
                                             target_dtype="bfloat16")
    assert str(new_args["fc_weight"].dtype) == "bfloat16"
    assert str(new_args["bn_gamma"].dtype) == "float32"
    assert str(new_aux["bn_moving_mean"].dtype) == "float32"


def test_amp_convert_symbol_inserts_casts_and_roundtrips():
    """VERDICT r4: the symbol graph pass inserts amp_cast nodes feeding
    listed ops, survives tojson/load_json, and evaluates close to the
    f32 original (the exported graph CARRIES its precision policy)."""
    import json
    import incubator_mxnet_tpu.symbol as S
    from incubator_mxnet_tpu.symbol import _eval_symbol, load_json

    rs = np.random.RandomState(4)
    x = S.var("data")
    y = S.FullyConnected(x, S.var("w"), S.var("b"), num_hidden=8,
                         name="fc")
    y = S.Activation(y, act_type="relu")
    y = S.softmax(y, axis=-1, name="sm")
    arg = {"w": nd.array(rs.randn(8, 6).astype(np.float32)),
           "b": nd.array(rs.randn(8).astype(np.float32))}
    xv = nd.array(rs.randn(3, 6).astype(np.float32))
    want = _eval_symbol(y, {"data": xv, **arg}).asnumpy()

    conv = amp.convert_symbol(y, target_dtype="bfloat16")
    graph = json.loads(conv.tojson())
    ops = [n["op"] for n in graph["nodes"]]
    assert "amp_cast" in ops, ops
    # fc inputs are cast to bf16; softmax input cast (back up) to f32
    cast_dtypes = [n["attrs"]["dtype"] for n in graph["nodes"]
                   if n["op"] == "amp_cast"]
    assert "bfloat16" in str(cast_dtypes) and "float32" in str(
        cast_dtypes), cast_dtypes

    # round-trip + numerics (bf16 matmul tolerance)
    rt = load_json(conv.tojson())
    got = _eval_symbol(rt, {"data": xv, **arg}).asnumpy()
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_amp_convert_symbol_shares_one_cast_per_producer():
    import json
    import incubator_mxnet_tpu.symbol as S

    x = S.var("data")
    a = S.FullyConnected(x, S.var("w1"), S.var("b1"), num_hidden=4,
                         name="fc1")
    b = S.FullyConnected(x, S.var("w2"), S.var("b2"), num_hidden=4,
                         name="fc2")
    g = a + b
    conv = amp.convert_symbol(g, target_dtype="bfloat16")
    graph = json.loads(conv.tojson())
    # 'data' feeds two fp16 ops but is cast ONCE
    data_casts = [n for n in graph["nodes"] if n["op"] == "amp_cast"
                  and "data_amp_cast" in n["name"]]
    assert len(data_casts) == 1, [n["name"] for n in graph["nodes"]]


def test_amp_multicast_op():
    a = nd.array(np.ones((2, 2), np.float32))
    b = nd.array(np.ones((2, 2)), dtype="bfloat16")
    o1, o2 = nd.invoke("amp_multicast", a, b, num_outputs=2)
    assert str(o1.dtype) == "float32" and str(o2.dtype) == "float32"
    n1, n2 = nd.invoke("amp_multicast", a, b, num_outputs=2,
                       cast_narrow=True)
    assert str(n1.dtype) == "bfloat16" and str(n2.dtype) == "bfloat16"


def test_amp_cast_op_leaves_ints_alone():
    idx = nd.array(np.array([1, 2], np.int32), dtype="int32")
    out = nd.invoke("amp_cast", idx, dtype="bfloat16")
    assert str(out.dtype) == "int32"
    f = nd.invoke("amp_cast", nd.array(np.ones(3, np.float32)),
                  dtype="bfloat16")
    assert str(f.dtype) == "bfloat16"
