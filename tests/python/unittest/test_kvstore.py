"""KVStore facade (ref: tests/python/unittest/test_kvstore.py — init/push/
pull invariants; exact-value asserts with deterministic inputs)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, kv
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_init_pull():
    store = kv.create("local")
    store.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    store.pull(3, out=out)
    assert_almost_equal(out, np.ones((2, 3)))


def test_push_aggregates():
    store = kv.create("local")
    store.init("w", nd.zeros((4,)))
    vals = [nd.ones((4,)), nd.ones((4,)) * 2, nd.ones((4,)) * 3]
    store.push("w", vals)
    out = nd.zeros((4,))
    store.pull("w", out=out)
    assert_almost_equal(out, np.full((4,), 6.0))


def test_pushpull_fused():
    store = kv.create("nccl")
    store.init(0, nd.zeros((3,)))
    a = nd.ones((3,))
    b = nd.ones((3,)) * 4
    store.pushpull(0, [a, b], out=[a, b])
    assert_almost_equal(a, np.full((3,), 5.0))
    assert_almost_equal(b, np.full((3,), 5.0))


def test_list_keys():
    store = kv.create("device")
    keys = [1, 2, 3]
    store.init(keys, [nd.ones((2,))] * 3)
    outs = [nd.zeros((2,)) for _ in keys]
    store.pull(keys, out=outs)
    for o in outs:
        assert_almost_equal(o, np.ones((2,)))


def test_set_optimizer_server_side_update():
    store = kv.create("local")
    store.init(0, nd.zeros((3,)))
    from incubator_mxnet_tpu import optimizer as opt
    store.set_optimizer(opt.SGD(learning_rate=1.0))
    store.push(0, nd.ones((3,)))       # grad=1, lr=1 → w -= 1
    out = nd.zeros((3,))
    store.pull(0, out=out)
    assert_almost_equal(out, -np.ones((3,)))


def test_row_sparse_pull():
    store = kv.create("local")
    w = nd.array(np.arange(12).reshape(4, 3).astype("float32"))
    store.init("emb", w)
    out = nd.zeros((4, 3))
    rows = nd.array([0, 2], dtype="int64")
    store.row_sparse_pull("emb", out=out, row_ids=rows)
    got = out.asnumpy()
    assert np.allclose(got[0], w.asnumpy()[0])
    assert np.allclose(got[2], w.asnumpy()[2])
    assert np.allclose(got[1], 0)


def test_gradient_compression_api():
    # in-process stores transfer nothing — compression must refuse, not
    # silently record (ref: compression is a ps-lite push-path feature)
    store = kv.create("device")
    with pytest.raises(mx.MXNetError):
        store.set_gradient_compression({"type": "2bit", "threshold": 0.5})

    dstore = kv.create("dist_sync")      # 1-process dist: honest fallback
    dstore.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    assert dstore._compression["type"] == "2bit"
    dstore.init(0, nd.zeros((3,)))
    dstore.push(0, nd.array(np.array([0.7, 0.3, -0.7], np.float32)))
    out = nd.zeros((3,))
    dstore.pull(0, out=out)
    # quantized to {-thr, 0, +thr}
    assert np.allclose(out.asnumpy(), [0.5, 0.0, -0.5])
    # error feedback: residual [0.2, 0.3, -0.2] carries into the next push
    dstore.push(0, nd.array(np.array([0.2, 0.3, 0.0], np.float32)))
    dstore.pull(0, out=out)
    assert np.allclose(out.asnumpy(), [0.0, 0.5, 0.0])


def test_rank_single_process():
    store = kv.create("local")
    assert store.rank == 0
    assert store.num_workers == 1


def test_invalid_type():
    with pytest.raises(mx.MXNetError):
        kv.create("bogus")


def test_optimizer_states_roundtrip(tmp_path):
    fname = str(tmp_path / "kv.states")
    store = kv.create("local")
    store.init(0, nd.zeros((2,)))
    from incubator_mxnet_tpu import optimizer as opt
    store.set_optimizer(opt.SGD(learning_rate=0.1, momentum=0.9))
    store.push(0, nd.ones((2,)))
    store.save_optimizer_states(fname)
    store.load_optimizer_states(fname)


def test_push_replaces_stored_value():
    """Regression: reference semantics — push REPLACES the stored value
    with the aggregate (init 2, push 8 → pull 8, not 10)."""
    store = kv.create("local")
    store.init("k", nd.ones((3,)) * 2)
    store.push("k", nd.ones((3,)) * 8)
    out = nd.zeros((3,))
    store.pull("k", out=out)
    assert_almost_equal(out, np.full((3,), 8.0))
    # and again: aggregate of a list replaces, not accumulates
    store.push("k", [nd.ones((3,)), nd.ones((3,)) * 4])
    store.pull("k", out=out)
    assert_almost_equal(out, np.full((3,), 5.0))


def test_pull_returns_fresh_buffer():
    """Regression: pull must hand out a COPY — with a server-side
    optimizer, the next push donates the stored weight buffer, which
    killed previously pulled aliases on real TPU."""
    from incubator_mxnet_tpu import optimizer as opt
    store = kv.create("local")
    store.set_optimizer(opt.create("sgd", learning_rate=0.1))
    store.init("w", nd.ones((4,)))
    out = nd.zeros((4,))
    store.pull("w", out=out)
    store.push("w", nd.ones((4,)))      # in-store update donates weight
    assert not out._data.is_deleted()
    assert_almost_equal(out, np.ones((4,)))
