"""SSD detector (BASELINE config 3) — training + detection smokes
(ref test model: example/ssd train/evaluate flow + GluonCV ssd tests)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon, autograd as ag
from incubator_mxnet_tpu.models.ssd import ssd_toy, ssd_training_targets


def _toy_batch(rs, B=8, size=32):
    """Images with one bright axis-aligned square; label = its box."""
    x = rs.rand(B, 3, size, size).astype(np.float32) * 0.1
    labels = np.full((B, 1, 5), -1, np.float32)
    for b in range(B):
        w = rs.randint(8, 16)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        x[b, :, y0:y0 + w, x0:x0 + w] = 1.0
        labels[b, 0] = [0, x0 / size, y0 / size, (x0 + w) / size,
                       (y0 + w) / size]
    return nd.array(x), nd.array(labels)


def test_ssd_forward_shapes():
    net = ssd_toy(classes=1)
    net.initialize()
    x = nd.array(np.random.rand(2, 3, 32, 32).astype(np.float32))
    anchors, cls_preds, box_preds = net(x)
    N = anchors.shape[1]
    assert anchors.shape == (1, N, 4)
    assert cls_preds.shape == (2, N, 2)
    assert box_preds.shape == (2, N * 4)
    # anchors cover multiple scales: 16x16*4 + 8x8*4
    assert N == 16 * 16 * 4 + 8 * 8 * 4


def test_ssd_training_targets_and_convergence():
    mx.random.seed(7)   # unseeded init + sgd momentum diverges for rare draws
    rs = np.random.RandomState(0)
    net = ssd_toy(classes=1)
    net.initialize()
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.02, "momentum": 0.9})
    x, labels = _toy_batch(rs)
    first = last = None
    for step in range(25):
        with ag.record():
            anchors, cls_preds, box_preds = net(x)
            loc_t, loc_m, cls_t = ssd_training_targets(anchors, cls_preds,
                                                       labels)
            B, N = cls_t.shape
            l_cls = cls_loss(cls_preds.reshape((B * N, -1)),
                             cls_t.reshape((-1,)))
            l_box = (nd.smooth_l1(box_preds - loc_t) * loc_m).mean()
            l = l_cls + l_box
            l.backward()
        trainer.step(x.shape[0])
        last = float(l.asnumpy().mean())
        if first is None:
            first = last
    assert last < first * 0.7, (first, last)
    # positive anchors exist for every image (force matching)
    assert (cls_t.asnumpy() > 0).sum() >= x.shape[0]


def test_ssd_detection_output():
    net = ssd_toy(classes=1)
    net.initialize()
    x = nd.array(np.random.rand(2, 3, 32, 32).astype(np.float32))
    anchors, cls_preds, box_preds = net(x)
    cls_prob = nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    det = nd.MultiBoxDetection(cls_prob, box_preds, anchors,
                               nms_threshold=0.5, threshold=0.01)
    B, N, C = det.shape
    assert C == 6                       # [cls, score, x1, y1, x2, y2]
    d = det.asnumpy()
    # a surviving detection has BOTH a class id and a score; NMS marks
    # suppressed rows with score -1
    valid = d[(d[:, :, 0] >= 0) & (d[:, :, 1] >= 0)]
    assert len(valid), "no detections survived NMS on random scores"
    assert (valid[:, 1] >= 0).all() and (valid[:, 1] <= 1).all()
    assert (valid[:, 2:] >= 0).all() and (valid[:, 2:] <= 1).all()


def test_ssd_train_loss_block_matches_eager():
    """SSDTrainLoss (the ONE-program train loss, r4) equals the eager
    targets+CE+smooth-L1 composition, and fuses when hybridized."""
    from incubator_mxnet_tpu.models import SSDTrainLoss
    rs = np.random.RandomState(2)
    net = ssd_toy(classes=3)
    net.initialize()
    x = nd.array(rs.randn(2, 3, 32, 32).astype(np.float32))
    lab = np.zeros((2, 1, 5), np.float32)
    lab[:, 0] = [1, .2, .2, .7, .7]
    y = nd.array(lab)
    anchors, cls_p, box_p = net(x)
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    loc_t, loc_m, cls_t = ssd_training_targets(anchors, cls_p, y)
    B, N = cls_t.shape
    ref = sce(cls_p.reshape((B * N, -1)),
              cls_t.reshape((-1,))).mean() + \
        (nd.smooth_l1(box_p - loc_t) * loc_m).mean()
    lb = SSDTrainLoss()
    lb.hybridize()
    got = lb(anchors, cls_p, box_p, y)
    np.testing.assert_allclose(got.asnumpy(), ref.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    # trains: loss decreases through the fused block
    net2 = ssd_toy(classes=3)
    net2.initialize()
    net2.hybridize()
    tr = gluon.Trainer(net2.collect_params(), "adam",
                       {"learning_rate": 2e-3})
    losses = []
    for _ in range(6):
        with ag.record():
            a2, c2, b2 = net2(x)
            l = lb(a2, c2, b2, y)
            l.backward()
        tr.step(2)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0], losses


def test_detection_loss_blocks_symbol_trace():
    """Both train-loss blocks must trace with Symbol inputs (the
    export path — review r4)."""
    import incubator_mxnet_tpu.symbol as S
    from incubator_mxnet_tpu.models import SSDTrainLoss, RCNNTrainLoss
    out = SSDTrainLoss()(S.var("a"), S.var("c"), S.var("b"),
                         S.var("l"))
    assert out.tojson()
    out2 = RCNNTrainLoss()(S.var("cp"), S.var("bp"), S.var("l"),
                           S.var("t"), S.var("w"))
    assert out2.tojson()
