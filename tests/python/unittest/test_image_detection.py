"""Detection augmenters + DLPack + inception_v3
(ref: tests/python/unittest/test_image.py TestImage.test_det_augmenters,
test_dlpack, model zoo tests)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_det_horizontal_flip_boxes():
    from incubator_mxnet_tpu.image import DetHorizontalFlipAug
    img = nd.array(np.random.rand(8, 6, 3).astype(np.float32))
    label = np.array([[0, 0.2, 0.3, 0.6, 0.7]], np.float32)
    img2, lab2 = DetHorizontalFlipAug(p=1.0)(img, label)
    assert abs(lab2[0, 1] - 0.4) < 1e-6
    assert abs(lab2[0, 3] - 0.8) < 1e-6
    # image flipped
    np.testing.assert_allclose(img2.asnumpy(), img.asnumpy()[:, ::-1])


def test_det_random_crop_keeps_and_renormalises():
    from incubator_mxnet_tpu.image import DetRandomCropAug
    np.random.seed(0)
    img = nd.array(np.random.rand(64, 48, 3).astype(np.float32))
    label = np.array([[0, 0.2, 0.3, 0.6, 0.7], [-1, 0, 0, 0, 0]],
                     np.float32)
    ci, cl = DetRandomCropAug(min_object_covered=0.5)(img, label)
    assert cl.shape == label.shape           # padded to same row count
    valid = cl[cl[:, 0] >= 0]
    assert len(valid) >= 1
    assert (valid[:, 1:5] >= -1e-6).all() and (valid[:, 1:5] <= 1 + 1e-6).all()


def test_det_augmenter_pipeline():
    from incubator_mxnet_tpu.image import CreateDetAugmenter
    np.random.seed(1)
    img = nd.array(np.random.rand(50, 70, 3).astype(np.float32) * 255)
    label = np.array([[1, 0.1, 0.1, 0.9, 0.9]], np.float32)
    augs = CreateDetAugmenter((3, 32, 32), rand_crop=0.5, rand_pad=0.5,
                              rand_mirror=True)
    for a in augs:
        img, label = a(img, label)
    assert img.shape == (32, 32, 3)
    assert label.shape[1] == 5


def test_dlpack_roundtrip_torch():
    import torch
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    t = torch.from_dlpack(nd.to_dlpack_for_read(x))
    np.testing.assert_allclose(np.asarray(t), x.asnumpy())
    back = nd.from_dlpack(torch.arange(4, dtype=torch.float32).reshape(2, 2))
    np.testing.assert_allclose(back.asnumpy(),
                               np.arange(4, dtype=np.float32).reshape(2, 2))
    # mx -> mx roundtrip through the protocol object
    r = nd.from_dlpack(nd.to_dlpack_for_read(x))
    np.testing.assert_allclose(r.asnumpy(), x.asnumpy())


def test_inception_v3_forward():
    from incubator_mxnet_tpu.gluon.model_zoo.vision import get_model
    net = get_model("inception_v3", classes=7)
    net.initialize()
    x = nd.array(np.random.rand(1, 3, 299, 299).astype(np.float32))
    out = net(x)
    assert out.shape == (1, 7)
