"""RNG facade (ref: tests/python/unittest/test_random.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_seed_reproducibility():
    mx.random.seed(42)
    a = nd.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = nd.uniform(shape=(5,)).asnumpy()
    assert np.allclose(a, b)
    c = nd.uniform(shape=(5,)).asnumpy()
    assert not np.allclose(b, c)      # keys split per call


def test_uniform_range():
    x = nd.random.uniform(low=2.0, high=3.0, shape=(1000,)).asnumpy()
    assert x.min() >= 2.0 and x.max() <= 3.0
    assert abs(x.mean() - 2.5) < 0.05


def test_normal_moments():
    x = nd.random.normal(loc=1.0, scale=2.0, shape=(20000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.1
    assert abs(x.std() - 2.0) < 0.1


def test_randint():
    x = nd.random.randint(low=0, high=10, shape=(1000,)).asnumpy()
    assert x.min() >= 0 and x.max() < 10
    assert x.dtype == np.int32


def test_poisson_gamma_exponential():
    p = nd.random.poisson(lam=4.0, shape=(5000,)).asnumpy()
    assert abs(p.mean() - 4.0) < 0.2
    g = nd.random.gamma(alpha=2.0, beta=3.0, shape=(5000,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.5
    e = nd.random.exponential(lam=2.0, shape=(5000,)).asnumpy()
    assert abs(e.mean() - 0.5) < 0.1


def test_multinomial():
    probs = nd.array([0.1, 0.0, 0.9])
    draws = nd.random.multinomial(probs, shape=(1000,)).asnumpy()
    assert (draws == 1).sum() == 0
    assert (draws == 2).mean() > 0.8


def test_sample_parametrized():
    mu = nd.array([0.0, 10.0])
    sigma = nd.array([1.0, 1.0])
    s = nd.random.normal(mu, sigma, shape=(500,)).asnumpy()
    assert s.shape == (2, 500)
    assert abs(s[0].mean()) < 0.3
    assert abs(s[1].mean() - 10) < 0.3


def test_shuffle():
    x = nd.arange(0, 100)
    y = nd.random.shuffle(x).asnumpy()
    assert sorted(y.tolist()) == list(range(100))
    assert not np.allclose(y, np.arange(100))


def test_per_context_independent_streams():
    mx.random.seed(7)
    a = nd.uniform(shape=(4,)).asnumpy()
    mx.random.seed(7, ctx=mx.cpu())
    b = nd.uniform(shape=(4,)).asnumpy()
    assert a.shape == b.shape
