"""Memory-observatory tests (telemetry.memwatch — ISSUE 20):
bounded sample ring, the disabled-is-one-bool-read contract, tenant
attribution join against a hand-built ledger (proportional shares +
the explicit unattributed row), the registry ledger's measured/drift
columns (None when stale), the >10% reconcile event, the mem-drift
rule lifecycle (fire → reconcile → clear) off an injected ledger, the
OOM forensics end-to-end drill (injected serve.oom fault → proactive
blackbox dump with a memwatch block → `blackbox memautopsy` verdict
naming the drifting tenant), the flightrec hbm_sample live_arrays
fallback, the export surfaces (Prometheus gauges + /metrics.json +
teletop pane), and the two-process durable-watermark proof.
CPU-only, fast (the overhead gate wrapper is slow-marked)."""
import gc
import json
import os
import subprocess
import sys

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, gluon, nd
from incubator_mxnet_tpu.monitor import events
from incubator_mxnet_tpu.serving import InferenceEngine, ModelRegistry
from incubator_mxnet_tpu.telemetry import flightrec as _bb
from incubator_mxnet_tpu.telemetry import history, memwatch, slo

pytestmark = pytest.mark.memwatch

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     "..", "..", ".."))


@pytest.fixture(autouse=True)
def clean_memwatch():
    """Fresh observatory state (ring, watermarks, sources, trainers,
    sampler, enable override) on both sides of every test.  The gc
    pass matters in the full corpus: a cycle-held ModelRegistry from
    an earlier suite stays in the live_registries() weak set until
    collected, and its ledger rows would pollute the hand-built
    attribution joins below.  Throttle off: these tests poll
    sample() far faster than any production cadence."""
    gc.collect()
    os.environ["MXNET_MEMWATCH_MIN_S"] = "0"
    memwatch.reset()
    yield
    memwatch.reset()
    os.environ.pop("MXNET_MEMWATCH_MIN_S", None)


@pytest.fixture
def hist_dir(tmp_path, monkeypatch):
    d = tmp_path / "hist"
    monkeypatch.setenv("MXNET_HISTORY_DIR", str(d))
    history.reset()
    slo.clear_rules()
    yield str(d)
    slo.clear_rules()
    history.reset()


def _sampler(used=900, device="cpu:0", source="test"):
    return lambda: {device: {"used_bytes": used, "peak_bytes": used,
                             "limit_bytes": 0, "source": source}}


def _dense_net(units=4, in_units=8, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(units))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    net(nd.array(onp.zeros((1, in_units), onp.float32), ctx=mx.cpu()))
    return net


# ---------------------------------------------------------------------------
# sampling: bounded ring, disabled-is-free, live_arrays fallback
# ---------------------------------------------------------------------------

def test_ring_bounded(monkeypatch):
    """The sample ring holds exactly MXNET_MEMWATCH_RING entries under
    churn (reset() re-sizes it from the knob)."""
    monkeypatch.setenv("MXNET_MEMWATCH_RING", "8")
    memwatch.reset()
    memwatch.set_sampler(_sampler())
    for i in range(30):
        assert memwatch.sample(tag="t%d" % i) is not None
    got = memwatch.samples()
    assert len(got) == 8
    # newest survive, oldest dropped
    assert [s["tag"] for s in got] == ["t%d" % i for i in range(22, 30)]
    assert memwatch.last_sample()["tag"] == "t29"


def test_disabled_is_free():
    """enable(False) turns sample() into a None-returning bool read:
    no ring append, no watermark, no counter."""
    memwatch.set_sampler(_sampler())
    before = events.snapshot().get("memwatch.samples", 0)
    prev = memwatch.enable(False)
    try:
        assert memwatch.sample() is None
        assert memwatch.samples() == []
        assert memwatch.last_sample() is None
        assert memwatch.watermarks() == {}
        assert events.snapshot().get("memwatch.samples", 0) == before
    finally:
        memwatch.enable(prev)
    # force=True (the OOM/dump path) samples anyway
    assert memwatch.sample(force=True) is not None


def test_real_probe_live_arrays_fallback():
    """On this CPU host PJRT memory_stats is None, so the real probe
    must fall back to the jax.live_arrays() sum, tagged with its
    source — the path every other platformless host takes."""
    keep = nd.ones((64, 64))                    # something resident
    s = memwatch.sample(tag="probe")
    assert s is not None and s["devices"]
    dev = s["devices"]["cpu:0"]
    assert dev["source"] == "live_arrays"
    assert dev["used_bytes"] >= keep.size * 4
    assert s["total_bytes"] >= dev["used_bytes"]


def test_flightrec_hbm_sample_fallback():
    """flightrec.hbm_sample (ISSUE 20 satellite): the hbm ring events
    no longer silently no-op on CPU — they carry the live_arrays sum
    with the source spelled out."""
    keep = nd.ones((32, 32))
    float(keep.sum().asscalar())
    _bb.hbm_sample(tag="t")
    evs = [e for e in _bb.ring_snapshot()
           if e.get("kind") == "hbm" and e.get("tag") == "t"]
    assert evs, "no hbm ring event on CPU — fallback regressed"
    assert evs[-1]["source"] == "live_arrays"
    assert evs[-1]["bytes_in_use"] > 0


def test_phase_watermarks():
    """Watermarks split per phase; the phase() scope samples on exit
    so a deploy spike lands under 'deploy', not 'steady'."""
    memwatch.set_sampler(_sampler(used=100))
    memwatch.sample()
    memwatch.set_sampler(_sampler(used=700))
    with memwatch.phase("deploy"):
        pass                        # exit takes the sample
    memwatch.set_sampler(_sampler(used=300))
    memwatch.sample()
    marks = memwatch.watermarks()
    assert marks["steady"]["cpu:0"] == 300
    assert marks["deploy"]["cpu:0"] == 700
    assert memwatch.current_phase() == "steady"


# ---------------------------------------------------------------------------
# attribution: proportional shares, unattributed remainder
# ---------------------------------------------------------------------------

def test_attribution_join_hand_built_ledger():
    memwatch.register_source("t", lambda: [
        {"tenant": "resnet", "device": "cpu:0",
         "committed_bytes": 300, "kind": "serve"},
        {"tenant": "bert", "device": "cpu:0",
         "committed_bytes": 100, "kind": "serve"}])
    memwatch.set_sampler(lambda: {
        "cpu:0": {"used_bytes": 800, "peak_bytes": 800,
                  "limit_bytes": 0, "source": "test"},
        "cpu:1": {"used_bytes": 500, "peak_bytes": 500,
                  "limit_bytes": 0, "source": "test"}})
    memwatch.sample()
    rows = memwatch.attribution()
    by = {(r["tenant"], r["device"]): r for r in rows}
    # proportional: 800 split 3:1
    assert by[("resnet", "cpu:0")]["measured_bytes"] == 600
    assert by[("resnet", "cpu:0")]["drift"] == 2.0
    assert by[("bert", "cpu:0")]["measured_bytes"] == 200
    # bytes nobody committed are an explicit row, not a silent gap
    un = by[("(unattributed)", "cpu:1")]
    assert un["measured_bytes"] == 500 and un["committed_bytes"] == 0
    assert un["drift"] is None
    # sorted biggest consumer first, top caps
    assert rows[0]["tenant"] == "resnet"
    assert len(memwatch.attribution(top=2)) == 2
    top = memwatch.top_consumers(2)
    assert top == {"resnet@cpu:0": 600, "(unattributed)@cpu:1": 500}


def test_attribution_device_name_normalization():
    """Context-style 'cpu(0)' ledger rows join against PJRT-style
    'cpu:0' sample keys."""
    memwatch.register_source("t", lambda: [
        {"tenant": "m", "device": "cpu(0)", "committed_bytes": 50}])
    memwatch.set_sampler(_sampler(used=100))
    memwatch.sample()
    rows = memwatch.attribution()
    assert rows[0]["tenant"] == "m"
    assert rows[0]["device"] == "cpu:0"
    assert rows[0]["measured_bytes"] == 100


# ---------------------------------------------------------------------------
# registry satellite: measured/drift ledger columns + reconcile event
# ---------------------------------------------------------------------------

def test_registry_ledger_measured_columns():
    """stats() ledger rows carry measured_bytes/drift from a FRESH
    sample and None when no sample exists — the reader always knows
    whether it is looking at measurement or the ledger again."""
    reg = ModelRegistry(devices=[mx.cpu(0)])
    try:
        # before ANY sample exists the columns must read None (the
        # register below takes a deploy-phase sample on its own)
        row = reg.stats()["ledger"][0]
        assert row["measured_bytes"] is None and row["drift"] is None
        reg.register("m", _dense_net(), example_shape=(8,))
        memwatch.set_sampler(_sampler(used=4096))
        memwatch.sample()
        row = reg.stats()["ledger"][0]
        assert row["measured_bytes"] == 4096
        assert row["drift"] == round(4096 / row["committed"], 4)
        # the registry row also shows up in the attribution join
        tenants = {r["tenant"] for r in memwatch.attribution()}
        assert "m" in tenants
    finally:
        reg.close()


def test_reconcile_large_event(monkeypatch):
    """A reconcile that moves a footprint >10% fires its own counter
    + ring event (prior vs measured vs pct) — drift trends are
    countable without parsing every reconcile."""
    from incubator_mxnet_tpu.telemetry import costs as _costs
    reg = ModelRegistry(devices=[mx.cpu(0)])
    try:
        reg.register("m", _dense_net(), example_shape=(8,))
        prior = reg.stats()["models"]["m"]["footprint_bytes"]
        before = events.snapshot().get(
            "serve.footprint_reconcile_large", 0)
        monkeypatch.setattr(_costs, "footprint_bytes",
                            lambda fam, kind=None: int(prior * 2))
        assert reg.reconcile("m") == prior * 2
        assert events.snapshot()["serve.footprint_reconcile_large"] \
            == before + 1
        evs = [e for e in _bb.ring_snapshot()
               if e.get("name") == "footprint_reconcile_large"
               and e.get("model") == "m"]
        assert evs and evs[-1]["prior_bytes"] == prior
        assert evs[-1]["measured_bytes"] == prior * 2
        assert abs(evs[-1]["pct_moved"] - 1.0) < 1e-6
        assert reg.stats()["models"]["m"]["basis"] == "measured"
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# the mem-drift rule: fire -> reconcile -> clear
# ---------------------------------------------------------------------------

def test_mem_drift_rule_fire_reconcile_clear(hist_dir):
    """The full lifecycle off a hand-built ledger: round 1 fires
    (measured 3x committed) and re-reconciles the ledger; round 2
    judges the reconciled ledger clean and clears."""
    ledger = {"resnet": 300, "bert": 290}
    measured = {"resnet": 900, "bert": 300}

    def rows_fn():
        return [{"tenant": t, "device": "cpu:0",
                 "committed_bytes": c,
                 "measured_bytes": measured[t], "source": "test"}
                for t, c in ledger.items()]

    reconciled = []

    def reconcile_fn(tenant):
        reconciled.append(tenant)
        ledger[tenant] = measured[tenant]
        return True

    slo.register_rule(slo.MemDriftRule(
        factor=1.5, rows_fn=rows_fn, reconcile_fn=reconcile_fn))
    slo.evaluate(now=1.0)
    active = slo.active_alerts()
    assert "mem-drift" in active
    info = active["mem-drift"]
    assert info["tenant"] == "resnet" and info["ratio"] == 3.0
    assert info["reconciled"] is True
    assert info["top"]["resnet@cpu:0"] == 900
    assert reconciled == ["resnet"]
    # the reconcile resolved the contradiction -> next round clears
    slo.evaluate(now=2.0)
    assert "mem-drift" not in slo.active_alerts()
    # bert never crossed the factor (300/290 ~ 1.03): one reconcile
    assert reconciled == ["resnet"]


def test_mem_drift_rule_unjudgeable_without_fresh_sample():
    """No injected rows and no fresh sample -> (None, {}): the rule
    abstains instead of judging stale evidence."""
    rule = slo.MemDriftRule(factor=1.5)
    firing, info = rule.check(0.0)
    assert firing is None and info == {}


def test_mem_drift_rule_fires_on_underuse_too():
    """Hoarding (measured far BELOW committed) is drift in the other
    direction — ledger nobody can use."""
    rule = slo.MemDriftRule(factor=1.5, rows_fn=lambda: [
        {"tenant": "m", "device": "cpu:0", "committed_bytes": 1000,
         "measured_bytes": 100, "source": "test"}],
        reconcile_fn=lambda t: True)
    firing, info = rule.check(0.0)
    assert firing is True and info["ratio"] == 10.0


# ---------------------------------------------------------------------------
# OOM forensics end-to-end: fault -> dump -> memautopsy verdict
# ---------------------------------------------------------------------------

def test_oom_autopsy_end_to_end(tmp_path, monkeypatch, capsys):
    """The whole drill on this CPU host: an injected serve.oom fault
    raises RESOURCE_EXHAUSTED inside warmup, the catch site takes a
    proactive blackbox dump whose memwatch block joins the hand-
    drifted ledger, and the memautopsy CLI names the drifting
    tenant."""
    monkeypatch.setenv("MXNET_BLACKBOX_DIR", str(tmp_path))
    memwatch.register_source("t", lambda: [
        {"tenant": "resnet", "device": "cpu:0",
         "committed_bytes": 300},
        {"tenant": "bert", "device": "cpu:0",
         "committed_bytes": 100}])
    memwatch.set_sampler(_sampler(used=900, source="live_arrays"))

    eng = InferenceEngine(_dense_net(), ctx=mx.cpu(), max_batch=4)
    fault.install("serve.oom", times=1)
    with pytest.raises(fault.TransientFault, match="RESOURCE_EXHAUSTED"):
        eng.warmup(example_shape=(8,))
    eng.close()

    path = _bb.last_dump_path()
    assert path and os.path.exists(path)
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["reason"] == "memwatch:oom:serve.warmup"
    blk = doc["memwatch"]
    assert blk["sample"]["devices"]["cpu:0"]["source"] == "live_arrays"
    tenants = {r["tenant"]: r for r in blk["attribution"]}
    assert tenants["resnet"]["measured_bytes"] == 675    # 900 * 3/4
    oom_evs = [e for e in doc["events"]
               if e.get("kind") == "memwatch" and e.get("name") == "oom"]
    assert oom_evs and oom_evs[-1]["site"] == "serve.warmup"

    from incubator_mxnet_tpu.tools import blackbox
    # the suspected-cause heuristic names the memwatch: family, not
    # the generic uncaught-exception line
    cause = blackbox.suspected_cause(doc)
    assert "allocation failure" in cause and "'resnet'" in cause
    assert blackbox.main(["memautopsy", path]) == 0
    out = capsys.readouterr().out
    assert "memautopsy" in out
    assert "verdict: tenant 'resnet'" in out
    assert "live_arrays" in out


def test_guard_oom_ignores_non_oom(tmp_path, monkeypatch):
    """Only allocator failures trigger the forensic dump — an
    ordinary exception through the same catch site must not."""
    monkeypatch.setenv("MXNET_BLACKBOX_DIR", str(tmp_path))
    before_dump = _bb.last_dump_path()      # process-global marker
    before_oom = events.snapshot().get("memwatch.oom", 0)
    assert memwatch.guard_oom("x", ValueError("bad shape")) is False
    assert _bb.last_dump_path() == before_dump
    assert events.snapshot().get("memwatch.oom", 0) == before_oom
    assert memwatch.is_oom(MemoryError())
    assert memwatch.is_oom(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert not memwatch.is_oom(ValueError("nope"))


# ---------------------------------------------------------------------------
# export surfaces: prometheus gauges, /metrics.json, teletop pane
# ---------------------------------------------------------------------------

def test_export_surfaces():
    memwatch.register_source("t", lambda: [
        {"tenant": "m", "device": "cpu:0", "committed_bytes": 100}])
    memwatch.set_sampler(_sampler(used=200))
    memwatch.sample()
    from incubator_mxnet_tpu.telemetry.export import MetricsExporter
    ex = MetricsExporter()
    text = ex.prometheus_text()
    assert 'mxnet_hbm_used_bytes{device="cpu:0",source="test"} 200' \
        in text
    assert 'mxnet_hbm_peak_bytes{device="cpu:0",phase="steady"} 200' \
        in text
    assert 'mxnet_hbm_committed_bytes{device="cpu:0",tenant="m"} 100' \
        in text
    snap = ex.json_dict()
    assert snap["memwatch"]["sample"]["devices"]["cpu:0"][
        "used_bytes"] == 200
    # the teletop pane renders from the same block
    from incubator_mxnet_tpu.tools import teletop
    out = teletop.render(snap)
    assert "memwatch (phase=steady" in out
    assert "cpu:0" in out


def test_block_empty_before_first_sample():
    assert memwatch.block() == {}
    from incubator_mxnet_tpu.telemetry.export import MetricsExporter
    assert "memwatch" not in MetricsExporter().json_dict()


def test_probe_throttle(monkeypatch):
    """Unforced polls inside MXNET_MEMWATCH_MIN_S reuse the previous
    sample (no re-probe, no ring growth); phase-transition samples
    bypass the throttle."""
    monkeypatch.setenv("MXNET_MEMWATCH_MIN_S", "60")
    calls = [0]

    def probe():
        calls[0] += 1
        return {"cpu:0": {"used_bytes": 100 * calls[0],
                          "peak_bytes": 0, "limit_bytes": 0,
                          "source": "test"}}

    memwatch.set_sampler(probe)
    memwatch.enable(True)
    first = memwatch.sample(tag="a")
    throttled = memwatch.sample(tag="b")
    assert throttled is first and calls[0] == 1
    assert len(memwatch.samples()) == 1
    with memwatch.phase("deploy"):
        pass                        # exit sample must really probe
    assert calls[0] == 2
    assert memwatch.sample(tag="c", force=True)["tag"] == "c"
    assert calls[0] == 3


# ---------------------------------------------------------------------------
# two-process durable-watermark proof
# ---------------------------------------------------------------------------

_RUN1 = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_HISTORY_DIR"] = sys.argv[1]
from incubator_mxnet_tpu.telemetry import history, memwatch
memwatch.set_sampler(lambda: {
    "cpu:0": {"used_bytes": 12345, "peak_bytes": 12345,
              "limit_bytes": 0, "source": "test"}})
assert memwatch.sample(tag="run1") is not None
history.flush()
print("RUN1_ID=%s" % history.get_writer().run)
"""


def test_two_process_watermark_history(hist_dir):
    """Run 1 (separate process) watermarks; run 2 (this process)
    queries the durable row by run id — the memory envelope survives
    the process that measured it."""
    env = dict(os.environ)
    env.pop("MXNET_HISTORY_DIR", None)
    res = subprocess.run(
        [sys.executable, "-c", _RUN1, hist_dir], cwd=_ROOT, env=env,
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-2000:]
    run1 = [ln for ln in res.stdout.splitlines()
            if ln.startswith("RUN1_ID=")][0].split("=", 1)[1]
    assert history.get_writer().run != run1
    rows = history.query("watermark", kind="memwatch", run=run1)
    assert rows, "run 1's watermark row not visible to run 2"
    r = rows[-1]
    assert r["v"] == 12345.0
    assert r["labels"] == {"device": "cpu:0", "phase": "steady",
                           "source": "test"}


# ---------------------------------------------------------------------------
# the overhead gate (slow: tier-1 skips it, CI runs it)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_memwatch_overhead_gate():
    """tools/check_overhead.py --what mem: memwatch-on vs memwatch-off
    serving loop stays under the 2% budget."""
    script = os.path.join(_ROOT, "tools", "check_overhead.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, os.path.abspath(script), "--what", "mem",
         "--requests", "400", "--repeats", "2"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "check_overhead_memwatch" in res.stdout
