"""Metrics (ref: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, metric


def test_accuracy():
    m = metric.Accuracy()
    pred = nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert acc == pytest.approx(2.0 / 3.0)


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]])
    label = nd.array([1, 0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_mae_mse_rmse():
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([[1.5], [1.0]])
    m = metric.MAE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.75)
    m = metric.MSE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx((0.25 + 1.0) / 2)
    m = metric.RMSE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(np.sqrt(0.625))


def test_cross_entropy_and_perplexity():
    pred = nd.array([[0.9, 0.1], [0.2, 0.8]])
    label = nd.array([0, 1])
    ce = metric.CrossEntropy()
    ce.update([label], [pred])
    expect = -(np.log(0.9) + np.log(0.8)) / 2
    assert ce.get()[1] == pytest.approx(expect, rel=1e-5)
    p = metric.Perplexity()
    p.update([label], [pred])
    assert p.get()[1] == pytest.approx(np.exp(expect), rel=1e-5)


def test_f1():
    m = metric.F1()
    pred = nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]])
    label = nd.array([1, 0, 0, 1])
    m.update([label], [pred])
    assert 0 < m.get()[1] <= 1


def test_composite_and_create():
    m = metric.create(["acc", "ce"])
    assert isinstance(m, metric.CompositeEvalMetric)
    pred = nd.array([[0.1, 0.9]])
    label = nd.array([1])
    m.update([label], [pred])
    names, values = m.get()
    assert len(names) == 2
    m2 = metric.create("accuracy")
    assert isinstance(m2, metric.Accuracy)


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).sum())
    m = metric.CustomMetric(feval, name="abssum")
    m.update([nd.array([1.0])], [nd.array([0.5])])
    assert m.get()[1] == pytest.approx(0.5)


def test_loss_metric():
    m = metric.Loss()
    m.update(None, [nd.array([1.0, 2.0, 3.0])])
    assert m.get()[1] == pytest.approx(2.0)
