"""contrib.text tests (ref: tests/python/unittest/test_contrib_text.py)."""
import collections

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str(" Life is great! \n life is "
                                         "good. \n", to_lower=False)
    assert c["is"] == 2 and c["Life"] == 1 and c["life"] == 1
    c2 = text.utils.count_tokens_from_str("Life is life", to_lower=True)
    assert c2["life"] == 2


def test_vocabulary_basic():
    counter = collections.Counter(["a", "b", "b", "c", "c", "c"])
    v = text.Vocabulary(counter, min_freq=2)
    assert len(v) == 3              # <unk>, c, b
    assert v.to_indices("c") == 1
    assert v.to_indices(["b", "zzz"]) == [2, 0]
    assert v.to_tokens([1, 2]) == ["c", "b"]
    assert "a" not in v


def test_vocabulary_reserved_and_limits():
    counter = collections.Counter("aabbbcdd")
    v = text.Vocabulary(counter, most_freq_count=2,
                        reserved_tokens=["<pad>"])
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    assert len(v) == 4              # unk + pad + top-2
    with pytest.raises(ValueError):
        text.Vocabulary(counter, unknown_token="<pad>",
                        reserved_tokens=["<pad>"])
    with pytest.raises(ValueError):
        v.to_tokens(99)


def test_custom_embedding_and_lookup(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=str(f))
    assert emb.vec_len == 3
    assert len(emb) == 3            # unk + 2
    v = emb.get_vecs_by_tokens("hello")
    assert v.asnumpy().tolist() == [1.0, 2.0, 3.0]
    vs = emb.get_vecs_by_tokens(["world", "nope"])
    assert vs.asnumpy()[0].tolist() == [4.0, 5.0, 6.0]
    assert vs.asnumpy()[1].tolist() == [0.0, 0.0, 0.0]
    emb.update_token_vectors("hello", mx.nd.array([9.0, 9.0, 9.0]))
    assert emb.get_vecs_by_tokens("hello").asnumpy().tolist() == [9.0] * 3


def test_custom_embedding_with_vocab(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("a 1.0 1.0\nb 2.0 2.0\nc 3.0 3.0\n")
    counter = collections.Counter(["b", "b", "zzz"])
    v = text.Vocabulary(counter)
    emb = text.CustomEmbedding(str(f), vocabulary=v)
    assert len(emb) == len(v)
    assert emb.get_vecs_by_tokens("b").asnumpy().tolist() == [2.0, 2.0]
    # in-vocab but no pretrained vector → zeros
    assert emb.get_vecs_by_tokens("zzz").asnumpy().tolist() == [0.0, 0.0]


def test_composite_embedding(tmp_path):
    f1 = tmp_path / "e1.txt"
    f1.write_text("a 1.0\nb 2.0\n")
    f2 = tmp_path / "e2.txt"
    f2.write_text("a 10.0 11.0\n")
    v = text.Vocabulary(collections.Counter(["a", "b"]))
    comp = text.CompositeEmbedding(v, [
        text.CustomEmbedding(str(f1)), text.CustomEmbedding(str(f2))])
    assert comp.vec_len == 3
    va = comp.get_vecs_by_tokens("a").asnumpy()
    assert va.tolist() == [1.0, 10.0, 11.0]


def test_embedding_feeds_gluon_embedding(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("x 1.0 0.0\ny 0.0 1.0\n")
    v = text.Vocabulary(collections.Counter(["x", "y"]))
    emb = text.CustomEmbedding(str(f), vocabulary=v)
    layer = mx.gluon.nn.Embedding(len(v), emb.vec_len)
    layer.initialize()
    layer.weight.set_data(emb.idx_to_vec)
    idx = mx.nd.array(v.to_indices(["x", "y"]), dtype="int32")
    out = layer(idx).asnumpy()
    assert out[0].tolist() == [1.0, 0.0]
    assert out[1].tolist() == [0.0, 1.0]


def test_pretrained_downloads_gated():
    with pytest.raises(RuntimeError, match="egress"):
        text.embedding.create("glove")
    assert "glove.6B.50d.txt" in \
        text.embedding.get_pretrained_file_names("glove")


def test_onnx_api_present():
    """contrib.onnx is implemented natively (hand-rolled protobuf wire
    format — no onnx package); full coverage lives in test_onnx.py."""
    from incubator_mxnet_tpu.contrib import onnx
    for fn in ("import_model", "export_model", "get_model_metadata",
               "import_to_gluon"):
        assert callable(getattr(onnx, fn))
    with pytest.raises(FileNotFoundError):
        onnx.import_model("/nonexistent/m.onnx")
