"""contrib.text tests (ref: tests/python/unittest/test_contrib_text.py)."""
import collections

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str(" Life is great! \n life is "
                                         "good. \n", to_lower=False)
    assert c["is"] == 2 and c["Life"] == 1 and c["life"] == 1
    c2 = text.utils.count_tokens_from_str("Life is life", to_lower=True)
    assert c2["life"] == 2


def test_vocabulary_basic():
    counter = collections.Counter(["a", "b", "b", "c", "c", "c"])
    v = text.Vocabulary(counter, min_freq=2)
    assert len(v) == 3              # <unk>, c, b
    assert v.to_indices("c") == 1
    assert v.to_indices(["b", "zzz"]) == [2, 0]
    assert v.to_tokens([1, 2]) == ["c", "b"]
    assert "a" not in v


def test_vocabulary_reserved_and_limits():
    counter = collections.Counter("aabbbcdd")
    v = text.Vocabulary(counter, most_freq_count=2,
                        reserved_tokens=["<pad>"])
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    assert len(v) == 4              # unk + pad + top-2
    with pytest.raises(ValueError):
        text.Vocabulary(counter, unknown_token="<pad>",
                        reserved_tokens=["<pad>"])
    with pytest.raises(ValueError):
        v.to_tokens(99)


def test_custom_embedding_and_lookup(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=str(f))
    assert emb.vec_len == 3
    assert len(emb) == 3            # unk + 2
    v = emb.get_vecs_by_tokens("hello")
    assert v.asnumpy().tolist() == [1.0, 2.0, 3.0]
    vs = emb.get_vecs_by_tokens(["world", "nope"])
    assert vs.asnumpy()[0].tolist() == [4.0, 5.0, 6.0]
    assert vs.asnumpy()[1].tolist() == [0.0, 0.0, 0.0]
    emb.update_token_vectors("hello", mx.nd.array([9.0, 9.0, 9.0]))
    assert emb.get_vecs_by_tokens("hello").asnumpy().tolist() == [9.0] * 3


def test_custom_embedding_with_vocab(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("a 1.0 1.0\nb 2.0 2.0\nc 3.0 3.0\n")
    counter = collections.Counter(["b", "b", "zzz"])
    v = text.Vocabulary(counter)
    emb = text.CustomEmbedding(str(f), vocabulary=v)
    assert len(emb) == len(v)
    assert emb.get_vecs_by_tokens("b").asnumpy().tolist() == [2.0, 2.0]
    # in-vocab but no pretrained vector → zeros
    assert emb.get_vecs_by_tokens("zzz").asnumpy().tolist() == [0.0, 0.0]


def test_composite_embedding(tmp_path):
    f1 = tmp_path / "e1.txt"
    f1.write_text("a 1.0\nb 2.0\n")
    f2 = tmp_path / "e2.txt"
    f2.write_text("a 10.0 11.0\n")
    v = text.Vocabulary(collections.Counter(["a", "b"]))
    comp = text.CompositeEmbedding(v, [
        text.CustomEmbedding(str(f1)), text.CustomEmbedding(str(f2))])
    assert comp.vec_len == 3
    va = comp.get_vecs_by_tokens("a").asnumpy()
    assert va.tolist() == [1.0, 10.0, 11.0]


def test_embedding_feeds_gluon_embedding(tmp_path):
    f = tmp_path / "emb.txt"
    f.write_text("x 1.0 0.0\ny 0.0 1.0\n")
    v = text.Vocabulary(collections.Counter(["x", "y"]))
    emb = text.CustomEmbedding(str(f), vocabulary=v)
    layer = mx.gluon.nn.Embedding(len(v), emb.vec_len)
    layer.initialize()
    layer.weight.set_data(emb.idx_to_vec)
    idx = mx.nd.array(v.to_indices(["x", "y"]), dtype="int32")
    out = layer(idx).asnumpy()
    assert out[0].tolist() == [1.0, 0.0]
    assert out[1].tolist() == [0.0, 1.0]


def test_pretrained_downloads_gated():
    with pytest.raises(RuntimeError, match="egress"):
        text.embedding.create("glove")
    assert "glove.6B.50d.txt" in \
        text.embedding.get_pretrained_file_names("glove")


def test_onnx_api_present():
    """contrib.onnx is implemented natively (hand-rolled protobuf wire
    format — no onnx package); full coverage lives in test_onnx.py."""
    from incubator_mxnet_tpu.contrib import onnx
    for fn in ("import_model", "export_model", "get_model_metadata",
               "import_to_gluon"):
        assert callable(getattr(onnx, fn))
    with pytest.raises(FileNotFoundError):
        onnx.import_model("/nonexistent/m.onnx")


def test_greedy_translate_overfit_gnmt():
    """Greedy decode (contrib.text.decode — the Sockeye beam_search
    role) reproduces a memorized target on an overfit tiny GNMT."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, gluon, autograd as ag
    from incubator_mxnet_tpu.models import GNMT
    from incubator_mxnet_tpu.contrib.text import greedy_translate

    mx.random.seed(11)
    vocab, bos, eos = 20, 1, 2
    net = GNMT(vocab, vocab, embed_dim=16, hidden=32, enc_layers=2,
               dec_layers=1)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-2})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    src = nd.array([[5, 6, 7, 8], [9, 10, 11, 12]], dtype="int32")
    tgt_full = np.array([[bos, 13, 14, eos], [bos, 15, 16, eos]],
                        np.int32)
    tgt_in = nd.array(tgt_full[:, :-1], dtype="int32")
    lab = nd.array(tgt_full[:, 1:].astype(np.float32))
    for _ in range(80):
        with ag.record():
            out = net(src, tgt_in)
            l = sce(out.reshape((-1, vocab)), lab.reshape((-1,)))
            l.backward()
        trainer.step(2)
    assert float(l.mean().asnumpy()) < 0.1

    got = greedy_translate(net, src, bos=bos, eos=eos, max_len=5)
    np.testing.assert_array_equal(got[:, :3], tgt_full[:, 1:])


def test_beam_translate_matches_greedy_at_k1_and_scores():
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models import Seq2Seq
    from incubator_mxnet_tpu.contrib.text import (greedy_translate,
                                                  beam_translate)

    mx.random.seed(3)
    vocab, bos, eos = 15, 1, 2
    net = Seq2Seq(vocab, vocab, embed_dim=8, hidden=16, num_layers=1)
    net.initialize()
    src = nd.array(np.random.RandomState(0).randint(3, vocab, (3, 5)),
                   dtype="int32")
    g = greedy_translate(net, src, bos=bos, eos=eos, max_len=6)
    b1, s1 = beam_translate(net, src, bos=bos, eos=eos, beam_size=1,
                            max_len=6, alpha=0.0)
    np.testing.assert_array_equal(g, b1)
    b4, s4 = beam_translate(net, src, bos=bos, eos=eos, beam_size=4,
                            max_len=6, alpha=0.0)
    assert b4.shape == (3, 6) and b4.dtype == np.int32
    # (no s4 >= s1 invariant: top-K pruning can evict the greedy
    # prefix mid-decode, so a wider beam may legitimately land on a
    # lower-scoring final sequence)
    assert np.isfinite(s1).all() and np.isfinite(s4).all()
