"""Multi-CONTROLLER ShardedTrainer: N localhost processes, each owning
a slice of a global device mesh, train one model in SPMD lockstep
(ref: the reference's multi-node data-parallel training over ps-lite /
launched by tools/launch.py; here the TPU-native form — jax.distributed
coordination + one global Mesh whose collectives compile into the step).

Run per worker (the pytest launcher in test_parallel.py does this):

    DMLC_NUM_WORKER=2 DMLC_WORKER_ID=<r> DMLC_PS_ROOT_PORT=<p> \
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python tests/nightly/dist_sharded_trainer.py <out_json>

Each process feeds ITS rows of a deterministic global batch; worker 0
writes the final loss and a param checksum, which the launcher compares
against a single-process 8-device run of the same schedule — the
multi-host result must match the single-host result exactly (same
global batch, same mesh size, same seeds).
"""
import json
import os
import sys

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

import incubator_mxnet_tpu as mx                       # noqa: E402
from incubator_mxnet_tpu import nd, gluon, parallel    # noqa: E402

GLOBAL_BATCH = 16
STEPS = 3


def build_trainer():
    mx.random.seed(31)
    net = gluon.nn.HybridSequential(prefix="dst_")
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu",
                           prefix="dst_d1_"),
            gluon.nn.Dense(4, in_units=16, prefix="dst_d2_"))
    net.initialize(force_reinit=True)
    net(nd.ones((2, 8)))
    return parallel.ShardedTrainer(net, optimizer="adam", lr=1e-2,
                                   zero=1)


def global_data(step):
    rs = np.random.RandomState(100 + step)
    x = rs.randn(GLOBAL_BATCH, 8).astype(np.float32)
    y = rs.randint(0, 4, GLOBAL_BATCH)
    return x, y


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    rank = jax.process_index()
    nproc = jax.process_count()
    trainer = build_trainer()
    ndev_global = trainer.mesh.devices.size
    rows = GLOBAL_BATCH // nproc

    loss = None
    for i in range(STEPS):
        x, y = global_data(i)
        lo, hi = rank * rows, (rank + 1) * rows
        loss = trainer.step(x[lo:hi], y[lo:hi],
                            rng_bits=jax.random.key_data(
                                jax.random.PRNGKey(i)))
    final_loss = float(loss)
    checksum = float(sum(float(abs(v).sum())
                         for v in trainer.params.values()))
    print("rank %d/%d devices=%d loss=%.6f checksum=%.6f"
          % (rank, nproc, ndev_global, final_loss, checksum))
    if rank == 0 and out_path:
        with open(out_path, "w") as f:
            json.dump({"loss": final_loss, "checksum": checksum,
                       "n_devices": ndev_global,
                       "n_processes": nproc}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
