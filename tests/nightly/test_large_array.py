"""Large-tensor smoke: indexing past 2**31 elements must use 64-bit
arithmetic end to end (ref: tests/nightly/test_large_array.py, the
int64 "large tensor support" tier).

Like the reference, large-tensor support is an opt-in flag —
``MXNET_INT64_TENSOR_SIZE=1`` (ref: the USE_INT64_TENSOR_SIZE build
flag) — because 64-bit index math costs speed/memory on every gather.
The flag is honored at import time, so the checks run in a fresh
subprocess with it set; without it, 32-bit gather indices silently
wrap past 2**31 (verified: that is exactly the failure this tier
exists to catch).  Arrays are int8 to keep the footprint ~2.2 GB per
live tensor; guarded by free host memory.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

_SCRIPT = r"""
import jax
# the axon sitecustomize force-selects the TPU platform; the config
# update wins (same recipe as tests/conftest.py) — and the TPU-side
# compiler rejects x64-index HLO anyway, so this tier is host-only
jax.config.update("jax_platforms", "cpu")
import numpy as np
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

LARGE = 2 ** 31 + 64

# -- 1-D: create / far-end write / read / reduce / take ------------
a = nd.zeros((LARGE,), dtype="int8")
assert a.size == LARGE > 2 ** 31
a[LARGE - 4:] = 3
assert int(a[2 ** 31 + 61].asscalar()) == 3, "far-end read wrapped"
assert int(a.sum().asscalar()) == 12, "reduction lost far-end elements"
idx = nd.array(np.array([0, LARGE - 1], np.int64), dtype="int64")
got = nd.take(a, idx).asnumpy()
np.testing.assert_array_equal(got, np.array([0, 3], np.int8))
del a, idx, got

# -- 2-D: row count * cols crosses the boundary --------------------
rows = 2 ** 21 + 1
b = nd.zeros((rows, 1024), dtype="int8")
assert b.size > 2 ** 31
b[rows - 1, 1023:] = 5
assert int(b[rows - 1, 1023].asscalar()) == 5
assert int(b.sum().asscalar()) == 5
# flat argmax past 2**31: dtype='int64' (the reference's large-tensor
# pattern — float32 index returns lose precision past 2**24)
flat = b.reshape((-1,))
pos = int(nd.argmax(flat, axis=0, dtype="int64").asscalar())
assert pos == rows * 1024 - 1, "argmax position truncated: %d" % pos
print("LARGE_OK")
"""


def _available_gb():
    try:
        return (os.sysconf("SC_AVPHYS_PAGES") *
                os.sysconf("SC_PAGE_SIZE")) / 2 ** 30
    except (ValueError, OSError):
        return 0.0


@pytest.mark.slow
@pytest.mark.skipif(_available_gb() < 16,
                    reason="large-tensor tier needs >=16 GB free host "
                           "memory")
def test_int64_indexing_with_flag():
    # slow-marked: ~190s of multi-GB allocations is the nightly tier
    # this directory is named for — inside the 870s tier-1 cap it was
    # starving the tail of the corpus (the fast flag-registration
    # check below stays in tier-1)
    env = dict(os.environ)
    env.update({"MXNET_INT64_TENSOR_SIZE": "1", "JAX_PLATFORMS": "cpu"})
    res = subprocess.run([sys.executable, "-c", _SCRIPT], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=900)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "LARGE_OK" in res.stdout


def test_flag_registered_and_off_by_default():
    from incubator_mxnet_tpu import config
    assert config.get("MXNET_INT64_TENSOR_SIZE") in (False, True)
    assert "MXNET_INT64_TENSOR_SIZE" in config.describe()
