"""Multi-worker dist_sync kvstore invariants — run as N localhost
processes (ref: tests/nightly/dist_sync_kvstore.py, launched by
tools/launch.py with the dmlc `local` tracker; here the launcher is
tests/python/unittest/test_kvstore_dist.py or a manual

    DMLC_NUM_WORKER=2 DMLC_PS_ROOT_PORT=<p> DMLC_WORKER_ID=<i> \
        python tests/nightly/dist_sync_kvstore.py

per worker).  Asserts are exact-value, deterministic-input — the same
contract as the reference's nightly test (init value; aggregate ==
sum over workers; row_sparse rows; 2-bit compression with residual)."""
import os
import sys

import numpy as np

import jax

# virtual CPU backend; the kvstore itself calls jax.distributed.initialize
jax.config.update("jax_platforms", "cpu")

import incubator_mxnet_tpu as mx                       # noqa: E402
from incubator_mxnet_tpu import nd, kvstore            # noqa: E402


def main():
    kv = kvstore.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    expect_nw = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    assert nw == expect_nw, (nw, expect_nw)

    # --- init/broadcast: worker 0's value wins everywhere -------------
    init_val = 7.0 if rank == 0 else 99.0
    kv.init(3, nd.array(np.full((4, 2), init_val, np.float32)))
    out = nd.zeros((4, 2))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 7.0), out.asnumpy()

    # --- push: stored value becomes sum over ALL workers --------------
    kv.push(3, nd.array(np.full((4, 2), float(rank + 1), np.float32)))
    kv.pull(3, out=out)
    expected = nw * (nw + 1) / 2.0          # 1 + 2 + ... + nw
    assert np.allclose(out.asnumpy(), expected), out.asnumpy()

    # --- a second round on the same key (no state leakage) ------------
    kv.push(3, nd.array(np.full((4, 2), 2.0, np.float32)))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 2.0 * nw), out.asnumpy()

    # --- row_sparse_pull ----------------------------------------------
    w = np.arange(12, dtype=np.float32).reshape(6, 2)
    kv.init(9, nd.array(w))
    rs = nd.zeros((6, 2))
    kv.row_sparse_pull(9, out=rs, row_ids=nd.array(
        np.array([1, 4], np.float32)))
    exp = np.zeros((6, 2), np.float32)
    exp[[1, 4]] = w[[1, 4]]
    assert np.allclose(rs.asnumpy(), exp), rs.asnumpy()

    # --- 2-bit gradient compression with error feedback ---------------
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(1, nd.zeros((4,)))
    kv.push(1, nd.array(np.array([0.7, -0.7, 0.1, -0.1], np.float32)))
    c = nd.zeros((4,))
    kv.pull(1, out=c)
    assert np.allclose(c.asnumpy(), [0.5 * nw, -0.5 * nw, 0.0, 0.0]), \
        c.asnumpy()
    # residuals [0.2, -0.2, 0.1, -0.1] make the next small push visible
    kv.push(1, nd.array(np.array([0.3, -0.3, 0.0, 0.0], np.float32)))
    kv.pull(1, out=c)
    assert np.allclose(c.asnumpy(), [0.5 * nw, -0.5 * nw, 0.0, 0.0]), \
        c.asnumpy()

    # --- end-to-end: gluon.Trainer dist data-parallel step ------------
    # every worker computes grads on ITS shard; after step(batch) all
    # workers hold the identical, analytically-expected weight
    from incubator_mxnet_tpu import gluon, autograd as ag
    mx.random.seed(123)                  # identical init on all workers
    net = gluon.nn.Dense(1, use_bias=False, in_units=3)
    net.initialize()
    w0 = net.weight.data().asnumpy().copy()          # (1, 3)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5}, kvstore="dist_sync")
    x_np = np.full((2, 3), float(rank + 1), np.float32)
    x = nd.array(x_np)
    with ag.record():
        loss = (net(x) ** 2).sum()
        loss.backward()
    trainer.step(2)
    # expected: w1 = w0 - lr/2 * sum_r grad_r,  grad_r = 2 Σ_b pred_b x_b
    grad_sum = np.zeros_like(w0)
    for r in range(nw):
        xr = np.full((2, 3), float(r + 1), np.float32)
        pred = xr.dot(w0.T)                          # (2, 1)
        grad_sum += 2.0 * (pred * xr).sum(axis=0, keepdims=True)
    w_expect = w0 - 0.5 / 2.0 * grad_sum
    w_got = net.weight.data().asnumpy()
    assert np.allclose(w_got, w_expect, rtol=1e-5, atol=1e-6), \
        (w_got, w_expect)

    kv._barrier()
    print("dist_sync_kvstore ok: rank %d/%d" % (rank, nw))
    return 0


if __name__ == "__main__":
    sys.exit(main())
